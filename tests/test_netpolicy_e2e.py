"""Packet-level e2e for space egress policy (VERDICT r1: "the network
subsystem is tested as text, not behavior").

Proves through the real daemon that a cell in a default-deny space cannot
open connections to an external network, while an allowlisted CIDR:port
succeeds — enforced by the native kukenet driver (xtables ABI) or the
iptables CLI, whichever the host has. An "external host" is simulated as a
named netns routed (not bridged) off the host, so cell traffic traverses
the FORWARD hook exactly like traffic leaving a TPU-VM.

Reference behaviors: internal/netpolicy (fail-closed per-space chains),
internal/firewall (admission), internal/cni (per-cell attach).
"""

from __future__ import annotations

import os
import subprocess
import time

import pytest

from kukeon_tpu.runtime.cells import namespace as nsb
from kukeon_tpu.runtime.net.kukenet import KUKENET, kukenet_usable

from tests.test_runtime_e2e import Daemon

pytestmark = pytest.mark.skipif(
    not (os.geteuid() == 0 and os.access(nsb.KUKECELL, os.X_OK)
         and kukenet_usable()),
    reason="needs root + kukecell + kukenet (xtables ABI)",
)

EXT_NS = "kuke-test-ext"
EXT_HOST_IF = "kuke-ext-h"
EXT_IP = "198.51.100.1"
BLOCKED_IP = "198.51.100.9"


def _sh(*argv: str, check: bool = True) -> subprocess.CompletedProcess:
    p = subprocess.run(argv, capture_output=True, text=True)
    if check and p.returncode != 0:
        raise AssertionError(f"{' '.join(argv)}: rc={p.returncode} {p.stderr}")
    return p


@pytest.fixture(scope="module")
def external_host():
    """A routed 'external host' at 198.51.100.1 (TEST-NET-2;
    the sandbox VM's own uplink squats TEST-NET-1) with listeners on 8080/9090."""
    _sh("ip", "netns", "del", EXT_NS, check=False)
    _sh("ip", "netns", "add", EXT_NS)
    _sh("ip", "link", "add", EXT_HOST_IF, "type", "veth",
        "peer", "name", "kuke-ext-c")
    _sh("ip", "link", "set", "kuke-ext-c", "netns", EXT_NS)
    _sh("ip", "addr", "add", "198.51.100.254/24", "dev", EXT_HOST_IF)
    _sh("ip", "link", "set", EXT_HOST_IF, "up")
    ns = ["ip", "netns", "exec", EXT_NS]
    _sh(*ns, "ip", "link", "set", "lo", "up")
    _sh(*ns, "ip", "addr", "add", f"{EXT_IP}/24", "dev", "kuke-ext-c")
    _sh(*ns, "ip", "link", "set", "kuke-ext-c", "up")
    _sh(*ns, "ip", "route", "add", "default", "via", "198.51.100.254")
    listeners = []
    # Hermetic python: the host's PYTHONPATH sitecustomize (TPU plugin)
    # stalls startup inside a netns; the listener needs none of it.
    clean_env = {k: v for k, v in os.environ.items()
                 if k not in ("PYTHONPATH", "PYTHONSTARTUP")}
    for port in (8080, 9090):
        listeners.append(subprocess.Popen(
            ns + ["python3", "-S", "-c",
                  "import socket\n"
                  "s = socket.socket()\n"
                  "s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)\n"
                  f"s.bind(('{EXT_IP}', {port}))\n"
                  "s.listen(16)\n"
                  "while True:\n"
                  "    c, _ = s.accept()\n"
                  f"    c.sendall(b'hello-{port}')\n"
                  "    c.close()\n"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            env=clean_env,
        ))
    # Both listeners answering from the host before any test runs.
    import socket as _socket
    for port in (8080, 9090):
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            try:
                c = _socket.create_connection((EXT_IP, port), timeout=1)
                c.close()
                break
            except OSError:
                time.sleep(0.2)
        else:
            raise RuntimeError(f"external listener :{port} never came up")
    yield EXT_IP
    for p in listeners:
        p.kill()
    _sh("ip", "netns", "del", EXT_NS, check=False)
    _sh("ip", "link", "del", EXT_HOST_IF, check=False)


def _purge_kukeon_links():
    """Remove leaked kukeon bridges/veths from earlier (possibly killed)
    daemons: a stale bridge keeps a connected route for its subnet and
    black-holes return traffic for any new daemon that re-allocates it."""
    out = subprocess.run(["ip", "-o", "link"], capture_output=True,
                         text=True).stdout
    for line in out.splitlines():
        name = line.split(":", 2)[1].strip().split("@")[0]
        if name.startswith(("k-", "kv-")):
            subprocess.run(["ip", "link", "del", name], capture_output=True)


@pytest.fixture
def daemon():
    # conftest globally disables net enforcement for hermeticity; this suite
    # exists to test the real thing.
    _purge_kukeon_links()
    d = Daemon(env_overrides={"KUKEON_NET_ENFORCE": "1"})
    yield d
    d.stop()
    _purge_kukeon_links()
    # Reset the filter table so a deny chain never leaks into other tests.
    subprocess.run([KUKENET, "apply"], input=(
        "policy INPUT ACCEPT\npolicy FORWARD ACCEPT\npolicy OUTPUT ACCEPT\n"
    ), capture_output=True, text=True)


PROBE = (
    "import socket,sys\n"
    "def probe(ip, port):\n"
    "    s = socket.socket()\n"
    "    s.settimeout(3)\n"
    "    try:\n"
    "        s.connect((ip, port))\n"
    "        data = s.recv(64).decode()\n"
    "        print(f'CONNECT {ip}:{port} OK {data}')\n"
    "    except Exception as e:\n"
    "        print(f'CONNECT {ip}:{port} FAIL {type(e).__name__}')\n"
    "    finally:\n"
    "        s.close()\n"
)


def _run_probe_cell(daemon, space: str, name: str, probes: list[tuple[str, int]]):
    body = PROBE + "\n".join(f"probe({ip!r}, {port})" for ip, port in probes)
    manifest = f"""
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {{name: {name}, space: {space}}}
spec:
  containers:
    - name: main
      command: ["python3", "-c", {body!r}]
      restartPolicy: {{policy: never}}
"""
    daemon.kuke("apply", "-f", "-", stdin_data=manifest)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        p = daemon.kuke("get", "cell", name, "--space", space, check=False)
        if "exited" in p.stdout:
            break
        time.sleep(0.3)
    return daemon.kuke("log", name, "--space", space).stdout


class TestEgressEnforcement:
    def test_default_deny_blocks_external(self, daemon, external_host):
        daemon.kuke("apply", "-f", "-", stdin_data="""
apiVersion: kukeon.io/v1beta1
kind: Space
metadata: {name: lockdown}
spec:
  network:
    egressDefault: deny
    egressAllow:
      - {cidr: 198.51.100.1/32, ports: [8080]}
""")
        log = _run_probe_cell(daemon, "lockdown", "denyprobe", [
            (EXT_IP, 8080),       # allowlisted -> must succeed
            (EXT_IP, 9090),       # listener up, not allowlisted -> dropped
            (BLOCKED_IP, 8080),   # other external IP -> dropped
        ])
        assert f"CONNECT {EXT_IP}:8080 OK hello-8080" in log
        assert f"CONNECT {EXT_IP}:9090 FAIL" in log
        assert f"CONNECT {BLOCKED_IP}:8080 FAIL" in log

    def test_default_allow_reaches_external(self, daemon, external_host):
        daemon.kuke("apply", "-f", "-", stdin_data="""
apiVersion: kukeon.io/v1beta1
kind: Space
metadata: {name: open}
spec:
  network: {egressDefault: allow}
""")
        log = _run_probe_cell(daemon, "open", "allowprobe", [
            (EXT_IP, 8080),
            (EXT_IP, 9090),
        ])
        assert f"CONNECT {EXT_IP}:8080 OK hello-8080" in log
        assert f"CONNECT {EXT_IP}:9090 OK hello-9090" in log

    def test_cell_has_bridge_ip(self, daemon):
        daemon.kuke("apply", "-f", "-", stdin_data="""
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: ipcell}
spec:
  containers:
    - name: main
      command: ["sh", "-c", "ip -o addr show dev eth0 | head -1; sleep 20"]
      restartPolicy: {policy: never}
""")
        time.sleep(2)
        p = daemon.kuke("get", "cell", "ipcell")
        log = daemon.kuke("log", "ipcell").stdout
        assert "eth0" in log and "inet " in log
        daemon.kuke("stop", "ipcell")
