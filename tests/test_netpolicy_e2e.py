"""Packet-level e2e for space egress policy (VERDICT r1: "the network
subsystem is tested as text, not behavior").

Proves through the real daemon that a cell in a default-deny space cannot
open connections to an external network, while an allowlisted CIDR:port
succeeds — enforced by the native kukenet driver (xtables ABI) or the
iptables CLI, whichever the host has. An "external host" is simulated as a
named netns routed (not bridged) off the host, so cell traffic traverses
the FORWARD hook exactly like traffic leaving a TPU-VM.

Reference behaviors: internal/netpolicy (fail-closed per-space chains),
internal/firewall (admission), internal/cni (per-cell attach).
"""

from __future__ import annotations

import os
import subprocess
import time

import pytest

from kukeon_tpu.runtime.cells import namespace as nsb
from kukeon_tpu.runtime.net.kukenet import KUKENET, kukenet_usable

from tests.test_runtime_e2e import Daemon

pytestmark = pytest.mark.skipif(
    not (os.geteuid() == 0 and os.access(nsb.KUKECELL, os.X_OK)
         and kukenet_usable()),
    reason="needs root + kukecell + kukenet (xtables ABI)",
)

EXT_NS = "kuke-test-ext"
EXT_HOST_IF = "kuke-ext-h"
EXT_IP = "198.51.100.1"
BLOCKED_IP = "198.51.100.9"


def _sh(*argv: str, check: bool = True) -> subprocess.CompletedProcess:
    p = subprocess.run(argv, capture_output=True, text=True)
    if check and p.returncode != 0:
        raise AssertionError(f"{' '.join(argv)}: rc={p.returncode} {p.stderr}")
    return p


@pytest.fixture(scope="module")
def external_host():
    """A routed 'external host' at 198.51.100.1 (TEST-NET-2;
    the sandbox VM's own uplink squats TEST-NET-1) with listeners on 8080/9090."""
    _sh("ip", "netns", "del", EXT_NS, check=False)
    _sh("ip", "netns", "add", EXT_NS)
    _sh("ip", "link", "add", EXT_HOST_IF, "type", "veth",
        "peer", "name", "kuke-ext-c")
    _sh("ip", "link", "set", "kuke-ext-c", "netns", EXT_NS)
    _sh("ip", "addr", "add", "198.51.100.254/24", "dev", EXT_HOST_IF)
    _sh("ip", "link", "set", EXT_HOST_IF, "up")
    ns = ["ip", "netns", "exec", EXT_NS]
    _sh(*ns, "ip", "link", "set", "lo", "up")
    _sh(*ns, "ip", "addr", "add", f"{EXT_IP}/24", "dev", "kuke-ext-c")
    _sh(*ns, "ip", "link", "set", "kuke-ext-c", "up")
    _sh(*ns, "ip", "route", "add", "default", "via", "198.51.100.254")
    listeners = []
    # Hermetic python: the host's PYTHONPATH sitecustomize (TPU plugin)
    # stalls startup inside a netns; the listener needs none of it.
    clean_env = {k: v for k, v in os.environ.items()
                 if k not in ("PYTHONPATH", "PYTHONSTARTUP")}
    for port in (8080, 9090):
        listeners.append(subprocess.Popen(
            ns + ["python3", "-S", "-c",
                  "import socket\n"
                  "s = socket.socket()\n"
                  "s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)\n"
                  f"s.bind(('{EXT_IP}', {port}))\n"
                  "s.listen(16)\n"
                  "while True:\n"
                  "    c, _ = s.accept()\n"
                  f"    c.sendall(b'hello-{port}')\n"
                  "    c.close()\n"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            env=clean_env,
        ))
    # Both listeners answering from the host before any test runs.
    import socket as _socket
    for port in (8080, 9090):
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            try:
                c = _socket.create_connection((EXT_IP, port), timeout=1)
                c.close()
                break
            except OSError:
                time.sleep(0.2)
        else:
            raise RuntimeError(f"external listener :{port} never came up")
    yield EXT_IP
    for p in listeners:
        p.kill()
    _sh("ip", "netns", "del", EXT_NS, check=False)
    _sh("ip", "link", "del", EXT_HOST_IF, check=False)


def _purge_kukeon_links():
    """Remove leaked kukeon bridges/veths and sandbox processes from earlier
    (possibly killed) daemons: a stale bridge keeps a connected route for
    its subnet and black-holes return traffic for any new daemon that
    re-allocates it, and a leaked cell keeps probing/answering with a
    same-named veth and a conflicting IP. Purge runs only while no daemon
    under test is alive, so every kukeon sandbox process found is a leak."""
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            comm = open(f"/proc/{pid}/comm").read().strip()
        except OSError:
            continue
        if comm in ("kukepause", "kukeshim", "kukecell"):
            subprocess.run(["kill", "-9", pid], capture_output=True)
    out = subprocess.run(["ip", "-o", "link"], capture_output=True,
                         text=True).stdout
    for line in out.splitlines():
        name = line.split(":", 2)[1].strip().split("@")[0]
        if name.startswith(("k-", "kv-")):
            subprocess.run(["ip", "link", "del", name], capture_output=True)


@pytest.fixture
def daemon():
    # conftest globally disables net enforcement for hermeticity; this suite
    # exists to test the real thing.
    _purge_kukeon_links()
    d = Daemon(env_overrides={"KUKEON_NET_ENFORCE": "1"})
    yield d
    d.stop()
    _purge_kukeon_links()
    # Reset the filter table so a deny chain never leaks into other tests.
    subprocess.run([KUKENET, "apply"], input=(
        "policy INPUT ACCEPT\npolicy FORWARD ACCEPT\npolicy OUTPUT ACCEPT\n"
    ), capture_output=True, text=True)


PROBE = (
    "import socket,sys\n"
    "def probe(ip, port):\n"
    "    s = socket.socket()\n"
    "    s.settimeout(10)\n"
    "    try:\n"
    "        s.connect((ip, port))\n"
    "        data = s.recv(64).decode()\n"
    "        print(f'CONNECT {ip}:{port} OK {data}')\n"
    "    except Exception as e:\n"
    "        print(f'CONNECT {ip}:{port} FAIL {type(e).__name__}')\n"
    "    finally:\n"
    "        s.close()\n"
)


def _run_probe_cell(daemon, space: str, name: str, probes: list[tuple[str, int]]):
    body = PROBE + "\n".join(f"probe({ip!r}, {port})" for ip, port in probes)
    manifest = f"""
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {{name: {name}, space: {space}}}
spec:
  containers:
    - name: main
      command: ["python3", "-c", {body!r}]
      restartPolicy: {{policy: never}}
"""
    daemon.kuke("apply", "-f", "-", stdin_data=manifest)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        p = daemon.kuke("get", "cell", name, "--space", space, check=False)
        if "exited" in p.stdout:
            break
        time.sleep(0.3)
    return daemon.kuke("log", name, "--space", space).stdout


class TestEgressEnforcement:
    def test_default_deny_blocks_external(self, daemon, external_host):
        daemon.kuke("apply", "-f", "-", stdin_data="""
apiVersion: kukeon.io/v1beta1
kind: Space
metadata: {name: lockdown}
spec:
  network:
    egressDefault: deny
    egressAllow:
      - {cidr: 198.51.100.1/32, ports: [8080]}
""")
        log = _run_probe_cell(daemon, "lockdown", "denyprobe", [
            (EXT_IP, 8080),       # allowlisted -> must succeed
            (EXT_IP, 9090),       # listener up, not allowlisted -> dropped
            (BLOCKED_IP, 8080),   # other external IP -> dropped
        ])
        assert f"CONNECT {EXT_IP}:8080 OK hello-8080" in log
        assert f"CONNECT {EXT_IP}:9090 FAIL" in log
        assert f"CONNECT {BLOCKED_IP}:8080 FAIL" in log

    def test_default_allow_reaches_external(self, daemon, external_host):
        daemon.kuke("apply", "-f", "-", stdin_data="""
apiVersion: kukeon.io/v1beta1
kind: Space
metadata: {name: open}
spec:
  network: {egressDefault: allow}
""")
        log = _run_probe_cell(daemon, "open", "allowprobe", [
            (EXT_IP, 8080),
            (EXT_IP, 9090),
        ])
        assert f"CONNECT {EXT_IP}:8080 OK hello-8080" in log
        assert f"CONNECT {EXT_IP}:9090 OK hello-9090" in log

    def test_cell_has_bridge_ip(self, daemon):
        daemon.kuke("apply", "-f", "-", stdin_data="""
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: ipcell}
spec:
  containers:
    - name: main
      command: ["sh", "-c", "ip -o addr show dev eth0 | head -1; sleep 20"]
      restartPolicy: {policy: never}
""")
        time.sleep(2)
        p = daemon.kuke("get", "cell", "ipcell")
        log = daemon.kuke("log", "ipcell").stdout
        assert "eth0" in log and "inet " in log
        daemon.kuke("stop", "ipcell")


class TestModelCellInPolicy:
    """BASELINE config 4: the model cell lives INSIDE the space network —
    served over its bridge IP, governed by the space's default-deny egress
    (VERDICT r3 weak 4: previously every model cell was pinned to the host
    network and exempt from the policy it was meant to demonstrate)."""

    def test_model_cell_served_in_space_and_denied_egress(
        self, daemon, external_host
    ):
        import json as _json

        d = daemon
        d.kuke("apply", "-f", "-", stdin_data="""
apiVersion: kukeon.io/v1beta1
kind: Space
metadata: {name: agents}
spec:
  network:
    egressDefault: deny
""")
        d.kuke("apply", "-f", "-", stdin_data="""
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: llm, space: agents}
spec:
  model: {model: tiny, chips: 1, port: 9494, numSlots: 2, maxSeqLen: 128}
""")
        # The cell must have a bridge IP (it is NOT on the host network).
        rec = _json.loads(d.kuke("--json", "get", "cells", "llm",
                                 "--space", "agents").stdout)
        ip = rec["status"]["ip"]
        assert ip, f"model cell got no bridge IP: {rec['status']}"

        # Health over the BRIDGE IP; the host port must NOT answer.
        import urllib.request

        deadline = time.monotonic() + 120
        healthy = False
        while time.monotonic() < deadline:
            try:
                r = urllib.request.urlopen(f"http://{ip}:9494/v1/health",
                                           timeout=1)
                healthy = _json.loads(r.read())["status"] == "ok"
                break
            except OSError:
                rec = _json.loads(d.kuke("--json", "get", "cells", "llm",
                                         "--space", "agents").stdout)
                st = rec["status"]["containers"][0]
                if st["state"] == "exited":
                    log = d.kuke("log", "llm", "--container", "model-server",
                                 "--space", "agents", check=False).stdout
                    raise AssertionError(
                        f"model server exited ({st['exitCode']}):\n{log}")
                time.sleep(1.0)
        assert healthy, "model cell not healthy over its bridge IP in 120s"
        try:
            urllib.request.urlopen("http://127.0.0.1:9494/v1/health", timeout=1)
            raise AssertionError("model server leaked onto the host loopback")
        except OSError:
            pass

        # An in-space client cell reaches the model over the bridge. (An
        # HTTP probe, not the banner PROBE: the model server sends nothing
        # until it gets a request, so a recv-first probe would time out on
        # a perfectly healthy connection.)
        http_probe = (
            "import urllib.request\n"
            f"r = urllib.request.urlopen('http://{ip}:9494/v1/health', timeout=5)\n"
            "print('HEALTH', r.status, r.read().decode())\n"
        )
        d.kuke("apply", "-f", "-", stdin_data=f"""
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {{name: client, space: agents}}
spec:
  containers:
    - name: main
      command: ["python3", "-c", {http_probe!r}]
      restartPolicy: {{policy: never}}
""")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            rec = _json.loads(d.kuke("--json", "get", "cells", "client",
                                     "--space", "agents").stdout)
            if rec["status"]["containers"][0]["state"] == "exited":
                break
            time.sleep(0.3)
        log = d.kuke("log", "client", "--space", "agents").stdout
        assert "HEALTH 200" in log, f"in-space client could not reach model:\n{log}"

        # ...while the model cell itself cannot reach an external host:
        # default-deny egress governs it like any other cell. Probe from
        # inside the model cell's own netns via a sibling container.
        d.kuke("apply", "-f", "-", stdin_data=f"""
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {{name: llm, space: agents}}
spec:
  model: {{model: tiny, chips: 1, port: 9494, numSlots: 2, maxSeqLen: 128}}
  containers:
    - name: probe
      command: ["python3", "-c", {PROBE + f"probe({EXT_IP!r}, 8080)"!r}]
      restartPolicy: {{policy: never}}
""")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            rec = _json.loads(d.kuke("--json", "get", "cells", "llm",
                                     "--space", "agents").stdout)
            states = {c["name"]: c["state"] for c in rec["status"]["containers"]}
            if states.get("probe") == "exited":
                break
            time.sleep(0.3)
        log = d.kuke("log", "llm", "--container", "probe",
                     "--space", "agents").stdout
        assert f"CONNECT {EXT_IP}:8080 FAIL" in log, (
            f"model cell reached an external host under default-deny:\n{log}")


class TestUDPAndICMP:
    """VERDICT r3 item 10: packet-level deny semantics beyond TCP — the DNS
    (UDP 53) allowlist is the first rule a real agent cell needs, and ICMP
    must fall to the default verdict like everything else."""

    @pytest.fixture(scope="class")
    def udp_listener(self, external_host):
        """UDP echo on EXT_IP:53 (the DNS port) and :5353 inside the
        external netns."""
        ns = ["ip", "netns", "exec", EXT_NS]
        clean_env = {k: v for k, v in os.environ.items()
                     if k not in ("PYTHONPATH", "PYTHONSTARTUP")}
        listeners = []
        for port in (53, 5353):
            listeners.append(subprocess.Popen(
                ns + ["python3", "-S", "-c",
                      "import socket\n"
                      "s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)\n"
                      f"s.bind(('{EXT_IP}', {port}))\n"
                      "while True:\n"
                      "    data, addr = s.recvfrom(512)\n"
                      f"    s.sendto(b'udp-echo-{port}:' + data, addr)\n"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                env=clean_env,
            ))
        import socket as _socket

        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            try:
                c = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
                c.settimeout(1)
                c.sendto(b"ping", (EXT_IP, 53))
                c.recvfrom(64)
                c.close()
                break
            except OSError:
                time.sleep(0.2)
        else:
            raise RuntimeError("udp listener never answered")
        yield EXT_IP
        for p in listeners:
            p.kill()

    UDP_PROBE = (
        "import socket\n"
        "def probe(ip, port):\n"
        "    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)\n"
        "    s.settimeout(10)\n"
        "    try:\n"
        "        s.sendto(b'hi', (ip, port))\n"
        "        data, _ = s.recvfrom(128)\n"
        "        print(f'UDP {ip}:{port} OK', data.decode())\n"
        "    except Exception as e:\n"
        "        print(f'UDP {ip}:{port} FAIL {type(e).__name__}')\n"
        "    finally:\n"
        "        s.close()\n"
    )

    def test_udp_dns_allowlist(self, daemon, udp_listener):
        """default-deny + udp:53 allow: DNS flows, other UDP ports drop."""
        d = daemon
        d.kuke("apply", "-f", "-", stdin_data=f"""
apiVersion: kukeon.io/v1beta1
kind: Space
metadata: {{name: dnsonly}}
spec:
  network:
    egressDefault: deny
    egressAllow:
      - {{cidr: {EXT_IP}/32, ports: [53], protocol: udp}}
""")
        body = self.UDP_PROBE + (
            f"probe({EXT_IP!r}, 53)\n"
            f"probe({EXT_IP!r}, 5353)\n"
        )
        manifest = f"""
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {{name: dnsprobe, space: dnsonly}}
spec:
  containers:
    - name: main
      command: ["python3", "-S", "-c", {body!r}]
      restartPolicy: {{policy: never}}
"""
        d.kuke("apply", "-f", "-", stdin_data=manifest)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            import json as _json

            rec = _json.loads(d.kuke("--json", "get", "cells", "dnsprobe",
                                     "--space", "dnsonly").stdout)
            if rec["status"]["containers"][0]["state"] == "exited":
                break
            time.sleep(0.3)
        log = d.kuke("log", "dnsprobe", "--space", "dnsonly").stdout
        assert f"UDP {EXT_IP}:53 OK" in log, log
        assert f"UDP {EXT_IP}:5353 FAIL" in log, log

    def test_udp_denied_without_allowlist(self, daemon, udp_listener):
        d = daemon
        d.kuke("apply", "-f", "-", stdin_data="""
apiVersion: kukeon.io/v1beta1
kind: Space
metadata: {name: nodns}
spec:
  network: {egressDefault: deny}
""")
        body = self.UDP_PROBE + f"probe({EXT_IP!r}, 53)\n"
        manifest = f"""
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {{name: noprobe, space: nodns}}
spec:
  containers:
    - name: main
      command: ["python3", "-S", "-c", {body!r}]
      restartPolicy: {{policy: never}}
"""
        d.kuke("apply", "-f", "-", stdin_data=manifest)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            import json as _json

            rec = _json.loads(d.kuke("--json", "get", "cells", "noprobe",
                                     "--space", "nodns").stdout)
            if rec["status"]["containers"][0]["state"] == "exited":
                break
            time.sleep(0.3)
        log = d.kuke("log", "noprobe", "--space", "nodns").stdout
        assert f"UDP {EXT_IP}:53 FAIL" in log, log

    ICMP_PROBE = (
        "import socket, struct, os, time\n"
        "def ping(ip):\n"
        "    s = socket.socket(socket.AF_INET, socket.SOCK_RAW,\n"
        "                      socket.IPPROTO_ICMP)\n"
        "    s.settimeout(10)\n"
        "    payload = struct.pack('!BBHHH', 8, 0, 0, os.getpid() & 0xFFFF, 1)\n"
        "    csum = 0\n"
        "    for i in range(0, len(payload), 2):\n"
        "        csum += (payload[i] << 8) + payload[i+1]\n"
        "    csum = ~((csum >> 16) + (csum & 0xFFFF)) & 0xFFFF\n"
        "    pkt = struct.pack('!BBHHH', 8, 0, csum, os.getpid() & 0xFFFF, 1)\n"
        "    try:\n"
        "        s.sendto(pkt, (ip, 0))\n"
        "        s.recvfrom(256)\n"
        "        print(f'ICMP {ip} OK')\n"
        "    except Exception as e:\n"
        "        print(f'ICMP {ip} FAIL {type(e).__name__}')\n"
        "    finally:\n"
        "        s.close()\n"
    )

    def test_icmp_follows_default_verdict(self, daemon, external_host):
        """ICMP echo: dropped under default-deny, flows under default-allow
        (the cell runs as root, so SOCK_RAW is available in its netns)."""
        d = daemon
        for space, default, expect in (("pingdeny", "deny", "FAIL"),
                                       ("pingok", "allow", "OK")):
            d.kuke("apply", "-f", "-", stdin_data=f"""
apiVersion: kukeon.io/v1beta1
kind: Space
metadata: {{name: {space}}}
spec:
  network: {{egressDefault: {default}}}
""")
            body = self.ICMP_PROBE + f"ping({EXT_IP!r})\n"
            manifest = f"""
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {{name: pinger, space: {space}}}
spec:
  containers:
    - name: main
      command: ["python3", "-S", "-c", {body!r}]
      restartPolicy: {{policy: never}}
"""
            d.kuke("apply", "-f", "-", stdin_data=manifest)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                import json as _json

                rec = _json.loads(d.kuke("--json", "get", "cells", "pinger",
                                         "--space", space).stdout)
                if rec["status"]["containers"][0]["state"] == "exited":
                    break
                time.sleep(0.3)
            log = d.kuke("log", "pinger", "--space", space).stdout
            assert f"ICMP {EXT_IP} {expect}" in log, f"{space}: {log}"


class TestSliceMeshRules:
    """Slice-aware networking at the packet level (BASELINE config 4 /
    north star: 'a Realm's default-deny mesh spans a v5e slice over the TPU
    host network'): a daemon discovering peer slice workers must admit the
    TPU runtime's DCN ports to those peers THROUGH a default-deny space,
    while everything else stays dropped."""

    def test_default_deny_admits_peer_worker_dcn(self, external_host):
        _purge_kukeon_links()
        # The external-host netns IP plays the PEER SLICE WORKER; 8471 is
        # the libtpu runtime gRPC port (net/slice.py DEFAULT_SLICE_PORTS).
        clean_env = {k: v for k, v in os.environ.items()
                     if k not in ("PYTHONPATH", "PYTHONSTARTUP")}
        ns = ["ip", "netns", "exec", EXT_NS]
        listener = subprocess.Popen(
            ns + ["python3", "-S", "-c",
                  "import socket\n"
                  "s = socket.socket()\n"
                  "s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)\n"
                  f"s.bind(('{EXT_IP}', 8471))\n"
                  "s.listen(4)\n"
                  "while True:\n"
                  "    c, _ = s.accept()\n"
                  "    c.sendall(b'dcn-grpc')\n"
                  "    c.close()\n"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            env=clean_env,
        )
        d = Daemon(env_overrides={
            "KUKEON_NET_ENFORCE": "1",
            "KUKEON_SLICE_WORKERS": f"10.0.0.250,{EXT_IP}",
            "TPU_WORKER_ID": "0",
        })
        try:
            import socket as _socket

            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                try:
                    c = _socket.create_connection((EXT_IP, 8471), timeout=1)
                    c.close()
                    break
                except OSError:
                    time.sleep(0.2)
            else:
                raise RuntimeError("dcn listener never came up")

            d.kuke("apply", "-f", "-", stdin_data="""
apiVersion: kukeon.io/v1beta1
kind: Space
metadata: {name: slice}
spec:
  network: {egressDefault: deny}
""")
            log = _run_probe_cell(d, "slice", "worker", [
                (EXT_IP, 8471),   # peer worker DCN port -> admitted
                (EXT_IP, 8080),   # same peer, non-DCN port -> dropped
            ])
            assert f"CONNECT {EXT_IP}:8471 OK dcn-grpc" in log, log
            assert f"CONNECT {EXT_IP}:8080 FAIL" in log, log
        finally:
            listener.kill()
            d.stop()
            _purge_kukeon_links()
            subprocess.run([KUKENET, "apply"], input=(
                "policy INPUT ACCEPT\npolicy FORWARD ACCEPT\npolicy OUTPUT ACCEPT\n"
            ), capture_output=True, text=True)


class TestAgentStackSharesModel:
    """BASELINE config 3: a 4-cell coding-agent Stack sharing one model
    cell — all four agents generate concurrently against the model over the
    space bridge, inside a default-deny space."""

    def test_four_agents_generate_against_shared_model(self, daemon):
        import json as _json
        import urllib.request

        d = daemon
        d.kuke("apply", "-f", "-", stdin_data="""
apiVersion: kukeon.io/v1beta1
kind: Space
metadata: {name: team}
spec:
  network: {egressDefault: deny}
---
apiVersion: kukeon.io/v1beta1
kind: Stack
metadata: {name: agents, space: team}
---
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: llm, space: team}
spec:
  model: {model: tiny, chips: 1, port: 9497, numSlots: 4, maxSeqLen: 128}
""")
        rec = _json.loads(d.kuke("--json", "get", "cells", "llm",
                                 "--space", "team").stdout)
        ip = rec["status"]["ip"]
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            try:
                urllib.request.urlopen(f"http://{ip}:9497/v1/health", timeout=1)
                break
            except OSError:
                time.sleep(1)
        else:
            raise AssertionError("model cell never healthy")

        agent_body = (
            "import json, urllib.request, os\n"
            f"req = urllib.request.Request('http://{ip}:9497/v1/generate',\n"
            "    data=json.dumps({'promptTokens': [3, 1, 4, 1, 5],\n"
            "                     'maxNewTokens': 6}).encode(),\n"
            "    headers={'Content-Type': 'application/json'})\n"
            "out = json.load(urllib.request.urlopen(req, timeout=120))\n"
            "print('AGENT', os.environ.get('KUKEON_CELL'), 'GOT',\n"
            "      out['numTokens'], 'tokens')\n"
        )
        for i in range(4):
            d.kuke("apply", "-f", "-", stdin_data=f"""
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {{name: agent{i}, space: team, stack: agents}}
spec:
  containers:
    - name: main
      command: ["python3", "-S", "-c", {agent_body!r}]
      restartPolicy: {{policy: never}}
""")
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            states = []
            for i in range(4):
                rec = _json.loads(d.kuke(
                    "--json", "get", "cells", f"agent{i}", "--space", "team",
                    "--stack", "agents").stdout)
                states.append(rec["status"]["containers"][0]["state"])
            if all(s == "exited" for s in states):
                break
            time.sleep(0.5)
        for i in range(4):
            log = d.kuke("log", f"agent{i}", "--space", "team",
                         "--stack", "agents").stdout
            assert f"AGENT agent{i} GOT 6 tokens" in log, f"agent{i}: {log}"
