"""Test bootstrap: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/parallelism tests
run against ``--xla_force_host_platform_device_count=8`` CPU devices, mirroring
the reference's strategy of testing against fakes rather than real systems
(reference: internal/ctr tests with fake containerd services,
SURVEY.md section 4).

Note: the axon TPU plugin registers itself via sitecustomize and pre-imports
jax, so env vars alone are too late — ``jax.config.update`` is the reliable
switch.
"""

import os

# Tests must never program real bridges/iptables, even when running as root
# on a host that has the binaries (the runtime's autodetection would).
os.environ["KUKEON_NET_ENFORCE"] = "0"

# Appended last so it wins over any caller-provided count. KUKEON_TEST_DEVICES
# overrides the virtual-chip count (the CI sharded-serving job runs the suite
# at 4 to prove the multi-chip tests hold on a different factorization).
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count="
    + os.environ.get("KUKEON_TEST_DEVICES", "8")
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from the tier-1 run "
        "(subprocess daemons, real multi-cell federation e2e)")
    config.addinivalue_line(
        "markers",
        "faults: tests that arm KUKEON_FAULTS (the fault-injection harness)")


@pytest.fixture
def chips2_mesh():
    """A 2-chip tensor-parallel serving mesh on the forced CPU devices —
    the `chips: 2` grant as the engine sees it. Any even virtual-device
    count satisfies it (8 locally, 4 in the CI sharded job)."""
    from kukeon_tpu.parallel import serving_mesh

    return serving_mesh(2)


@pytest.fixture(autouse=True)
def _isolate_profile_spool(tmp_path, monkeypatch):
    """Point the on-demand profiler spool (KUKEON_PROFILE_DIR) at a per-test
    temp dir: captures from one test must never satisfy another test's
    listing, and the shared /tmp default must never accumulate CI garbage."""
    monkeypatch.setenv("KUKEON_PROFILE_DIR", str(tmp_path / "profiles"))


_SANITIZE_SESSION = False   # KUKEON_SANITIZE was set when the session began


def pytest_sessionstart(session):
    """Latch the sanitizer opt-in at session start: individual tests
    monkeypatch KUKEON_SANITIZE for their fixtures, and the per-test gate
    below must key off the *session-level* opt-in, not whatever a test
    left in the environment."""
    global _SANITIZE_SESSION
    from kukeon_tpu import sanitize

    _SANITIZE_SESSION = sanitize.enabled()


@pytest.fixture(autouse=True)
def _sanitize_findings_gate():
    """kukesan per-test gate: under a KUKEON_SANITIZE=1 session, any
    sanitizer finding a test produced (unguarded write to lock-guarded
    state, blocking call under a hot lock, observed lock-order cycle)
    fails THAT test with the recorded stacks. Findings are drained either
    way so fixture tests that deliberately provoke them stay isolated."""
    from kukeon_tpu import sanitize

    leftover = sanitize.drain_findings()
    yield
    found = sanitize.drain_findings()
    if _SANITIZE_SESSION:
        if leftover:
            # Produced between tests (teardown threads of an earlier
            # test): surface rather than silently blaming nobody.
            found = leftover + found
        assert not found, (
            "kukesan findings:\n\n"
            + "\n\n".join(f.render() for f in found))


def pytest_sessionfinish(session, exitstatus):
    """Close the static/dynamic loop: at the end of a sanitized session,
    write the merged lock-graph report (runtime-observed edges vs the
    KUKE006 static graph) to KUKEON_SANITIZE_REPORT when set."""
    out = os.environ.get("KUKEON_SANITIZE_REPORT")
    if not out or not _SANITIZE_SESSION:
        return
    import json

    from kukeon_tpu import sanitize

    with open(out, "w", encoding="utf-8") as f:
        json.dump(sanitize.merge_report(), f, indent=2)


@pytest.fixture(autouse=True)
def _isolate_telemetry_env(monkeypatch):
    """The RPC service constructs a FleetTelemetry (TSDB + alert engine)
    on every instantiation; stray alert-rule / retention env from one test
    must never rewire another test's daemon."""
    for var in ("KUKEON_ALERT_RULES", "KUKEON_ALERT_WEBHOOK",
                "KUKEON_SCRAPE_INTERVAL_S", "KUKEON_TSDB_RETENTION_S",
                "KUKEON_TSDB_MAX_SERIES", "KUKEON_SCALER_DRAIN_TIMEOUT_S"):
        monkeypatch.delenv(var, raising=False)


@pytest.fixture(autouse=True)
def _isolate_faults():
    """Guarantee KUKEON_FAULTS never leaks between tests: an armed fault
    spec surviving one test would fire random failures in the next. Cleared
    (and the parsed table + fire counts reset) on both sides of every test;
    tests arm faults by setting os.environ inside their own body."""
    from kukeon_tpu import faults

    os.environ.pop(faults.ENV, None)
    faults.reset()
    yield
    os.environ.pop(faults.ENV, None)
    faults.reset()
