"""kukelint (kukeon_tpu/analysis): fixture snippets per rule (positive +
negative), baseline suppression round-trip, and the tier-1 self-check that
runs the full analyzer over the real package — the static half of the
invariants the serving/runtime tests enforce dynamically.

Fixtures build a miniature repo under tmp_path (README.md + a package dir
with a ``serving/engine.py`` / ``faults.py`` where a rule needs one) so
every rule is proven to fire on a violation and stay silent on conforming
code, independent of the real tree's state.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from kukeon_tpu import faults
from kukeon_tpu.analysis import (
    Baseline,
    BaselineEntry,
    registered_rules,
    run_analysis,
)
from kukeon_tpu.analysis.__main__ import main as kukelint_main

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_ROOT = os.path.dirname(os.path.abspath(faults.__file__))

# A minimal engine skeleton the hostsync/jit fixtures extend: the seams,
# two jitted programs (one with a static position), nothing else.
ENGINE_HEADER = '''\
import jax
import jax.numpy as jnp
import numpy as np


class ServingEngine:
    def _fetch(self, x):
        return np.asarray(x)

    def _upload(self, x):
        return jnp.asarray(x)

    def _build_programs(self):
        def insert(state, kv, length, slot, token):
            return state

        def decode_chunk_fn(params, state, key, n_steps):
            return state, key

        self._insert = jax.jit(insert, donate_argnums=(0,))
        self._decode_chunk = jax.jit(decode_chunk_fn, static_argnums=(3,))
'''


def _mini_repo(tmp_path, files: dict[str, str], readme: str = "docs\n"):
    """Write a throwaway repo (README + package) and return its package
    root for run_analysis."""
    tmp_path.mkdir(parents=True, exist_ok=True)
    (tmp_path / "README.md").write_text(readme)
    pkg = tmp_path / "pkg"
    for rel, body in files.items():
        path = pkg / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(body))
    return str(pkg)


def _engine_repo(tmp_path, methods: str, readme: str = "docs\n"):
    return _mini_repo(
        tmp_path,
        {"serving/engine.py": ENGINE_HEADER + textwrap.indent(
            textwrap.dedent(methods), "    ")},
        readme=readme,
    )


def _rules(findings):
    return [f.rule for f in findings]


# --- KUKE001: device→host discipline -----------------------------------------


def test_kuke001_flags_raw_readbacks(tmp_path):
    pkg = _engine_repo(tmp_path, '''
        def step(self):
            toks = self._decode_chunk(self.params, self.state, 0, 4)
            a = int(toks[0, 0])
            b = np.asarray(self.state.tokens)
            c = toks.item()
            jax.device_get(toks)
            toks.block_until_ready()
            return a, b, c
    ''')
    found = run_analysis(pkg, select=["KUKE001"])
    details = sorted(f.detail for f in found)
    assert details == ["block_until_ready", "coerce.int", "device_get",
                       "item", "np.asarray"]
    assert all(f.rule == "KUKE001" for f in found)
    assert all(f.file.endswith("serving/engine.py") for f in found)


def test_kuke001_silent_on_routed_and_metadata(tmp_path):
    pkg = _engine_repo(tmp_path, '''
        def step(self):
            toks = self._fetch(self._decode_chunk(self.params, self.state, 0, 4))
            a = int(toks[0, 0])            # host numpy: fine
            n = int(self.state.tokens.shape[0])   # static metadata: fine
            p = np.asarray([1, 2], np.int32)      # host literal: fine
            return a, n, p
    ''')
    assert run_analysis(pkg, select=["KUKE001"]) == []


# --- KUKE002: host→device discipline -----------------------------------------


def test_kuke002_flags_raw_upload_and_respects_scope(tmp_path):
    pkg = _engine_repo(tmp_path, '''
        def step(self):
            return self._decode_chunk(self.params, jnp.asarray([0]), 0, 4)

        def precompile(self):
            return jnp.asarray([0])   # not a hot-path method: out of scope
    ''')
    found = run_analysis(pkg, select=["KUKE002"])
    assert _rules(found) == ["KUKE002"]
    assert found[0].scope == "ServingEngine.step"


def test_kuke002_silent_when_routed_through_upload(tmp_path):
    pkg = _engine_repo(tmp_path, '''
        def step(self):
            return self._decode_chunk(
                self.params, self._upload([0]), 0, 4)
    ''')
    assert run_analysis(pkg, select=["KUKE002"]) == []


# --- KUKE003: containers in traced positions ---------------------------------


def test_kuke003_flags_container_in_traced_position(tmp_path):
    pkg = _engine_repo(tmp_path, '''
        def step(self):
            s1 = self._insert(self.state, [1, 2], 8, 0, 1)
            s2 = self._insert.lower(self.state, [1, 2], 8, 0, 1).compile()
            return s1, s2
    ''')
    found = run_analysis(pkg, select=["KUKE003"])
    assert _rules(found) == ["KUKE003", "KUKE003"]
    assert all(f.detail == "_insert[1]" for f in found)


def test_kuke003_static_positions_are_exempt(tmp_path):
    pkg = _engine_repo(tmp_path, '''
        def step(self, kv):
            # arg 3 is static_argnums on _decode_chunk: containers allowed.
            return self._decode_chunk(self.params, self.state, 0, (1, 2))
    ''')
    assert run_analysis(pkg, select=["KUKE003"]) == []


# --- KUKE004: closures over mutable engine state -----------------------------


def test_kuke004_flags_mutable_closure(tmp_path):
    pkg = _mini_repo(tmp_path, {"serving/engine.py": '''\
        import jax


        class ServingEngine:
            def _build_programs(self):
                def insert(state, kv, length, slot, token):
                    return state, self._slot_len[slot]

                self._insert = jax.jit(insert, donate_argnums=(0,))
    '''})
    found = run_analysis(pkg, select=["KUKE004"])
    assert _rules(found) == ["KUKE004"]
    assert found[0].detail == "self._slot_len"


def test_kuke004_allows_frozen_config(tmp_path):
    pkg = _mini_repo(tmp_path, {"serving/engine.py": '''\
        import jax


        class ServingEngine:
            def _build_programs(self):
                def insert(state, kv, length, slot, token):
                    return state, min(self._bucket(length), self.max_seq_len)

                self._insert = jax.jit(insert, donate_argnums=(0,))
    '''})
    assert run_analysis(pkg, select=["KUKE004"]) == []


# --- KUKE014: explicit shardings on jitted-program definitions ---------------


def test_kuke014_flags_implicit_placement(tmp_path):
    # ENGINE_HEADER's two jit calls pass neither in_ nor out_shardings:
    # both programs are findings, keyed by program attribute.
    pkg = _engine_repo(tmp_path, "")
    found = run_analysis(pkg, select=["KUKE014"])
    assert _rules(found) == ["KUKE014", "KUKE014"]
    assert sorted(f.detail for f in found) == ["_decode_chunk", "_insert"]
    assert all(f.scope == "ServingEngine._build_programs" for f in found)


def test_kuke014_flags_half_specified_jit(tmp_path):
    pkg = _mini_repo(tmp_path, {"serving/engine.py": '''\
        import jax


        class ServingEngine:
            def _build_programs(self):
                def insert(state, kv, length, slot, token):
                    return state

                self._insert = jax.jit(
                    insert, donate_argnums=(0,),
                    in_shardings=(None, None, None, None, None))
    '''})
    found = run_analysis(pkg, select=["KUKE014"])
    assert _rules(found) == ["KUKE014"]
    assert "out_shardings" in found[0].message
    assert "in_shardings" not in found[0].message.split(":", 1)[1].split(
        "out_shardings")[0]


def test_kuke014_silent_with_explicit_shardings(tmp_path):
    # Replication is fine as long as it is spelled: both keywords present
    # (through the ct.wrap seam, like the real engine) satisfy the rule.
    pkg = _mini_repo(tmp_path, {"serving/engine.py": '''\
        import jax


        class ServingEngine:
            def _build_programs(self):
                def insert(state, kv, length, slot, token):
                    return state

                def decode_chunk_fn(params, state, key, n_steps):
                    return state, key

                repl = None
                self._insert = self.compiles.wrap(jax.jit(
                    insert, donate_argnums=(0,),
                    in_shardings=(repl,) * 5, out_shardings=repl), "insert")
                self._decode_chunk = jax.jit(
                    decode_chunk_fn, static_argnums=(3,),
                    in_shardings=(repl, repl, repl),
                    out_shardings=(repl, repl))
    '''})
    assert run_analysis(pkg, select=["KUKE014"]) == []


# --- KUKE015: jitted programs register with the program-timer seam -----------


def test_kuke015_flags_unregistered_programs(tmp_path):
    # Bare jax.jit (no wrap at all) and a wrap WITHOUT timer= are both
    # invisible to the per-program timers: two findings, keyed by
    # program attribute.
    pkg = _mini_repo(tmp_path, {"serving/engine.py": '''\
        import jax


        class ServingEngine:
            def _build_programs(self):
                def insert(state, kv, length, slot, token):
                    return state

                def decode_chunk_fn(params, state, key, n_steps):
                    return state, key

                ct = self.compiles
                self._insert = jax.jit(insert, donate_argnums=(0,))
                self._decode_chunk = ct.wrap(
                    jax.jit(decode_chunk_fn, static_argnums=(3,)),
                    "decode")
    '''})
    found = run_analysis(pkg, select=["KUKE015"])
    assert _rules(found) == ["KUKE015", "KUKE015"]
    assert sorted(f.detail for f in found) == ["_decode_chunk", "_insert"]
    assert all(f.scope == "ServingEngine._build_programs" for f in found)
    assert all("timer=" in f.message for f in found)


def test_kuke015_silent_with_timer_registration(tmp_path):
    pkg = _mini_repo(tmp_path, {"serving/engine.py": '''\
        import jax


        class ServingEngine:
            def _build_programs(self):
                def insert(state, kv, length, slot, token):
                    return state

                def decode_chunk_fn(params, state, key, n_steps):
                    return state, key

                ct = self.compiles
                tm = self.timers
                self._insert = ct.wrap(
                    jax.jit(insert, donate_argnums=(0,)), "insert",
                    timer=tm.track("insert"))
                self._decode_chunk = ct.wrap(
                    jax.jit(decode_chunk_fn, static_argnums=(3,)),
                    "decode", timer=tm.track("decode_chunk"))
    '''})
    assert run_analysis(pkg, select=["KUKE015"]) == []


# --- KUKE005: locked-somewhere means locked-everywhere -----------------------

LOCKED_CLASS = '''
    import threading


    class Engine:
        def __init__(self):
            self._lock = threading.Lock()
            self.depth = 0

        def locked_bump(self):
            with self._lock:
                self.depth += 1
'''


def test_kuke005_flags_unlocked_write(tmp_path):
    pkg = _mini_repo(tmp_path, {"runtime/thing.py": LOCKED_CLASS + '''
        def racy(self):
            self.depth = 5
    '''})
    found = run_analysis(pkg, select=["KUKE005"])
    assert _rules(found) == ["KUKE005"]
    assert found[0].detail == "depth"
    assert found[0].scope == "Engine.racy"


def test_kuke005_allows_init_and_call_mediated_lock_context(tmp_path):
    pkg = _mini_repo(tmp_path, {"runtime/thing.py": LOCKED_CLASS + '''
        def outer(self):
            with self._lock:
                self._reset()

        def _reset(self):
            # Every intra-class call site holds the lock: counts as locked.
            self.depth = 0
    '''})
    assert run_analysis(pkg, select=["KUKE005"]) == []


# --- KUKE006: lock-order cycles ----------------------------------------------


def test_kuke006_flags_lexical_order_cycle(tmp_path):
    pkg = _mini_repo(tmp_path, {"runtime/thing.py": '''
        import threading


        class C:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def one(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def two(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
    '''})
    found = run_analysis(pkg, select=["KUKE006"])
    assert _rules(found) == ["KUKE006"]
    assert "_a_lock" in found[0].detail and "_b_lock" in found[0].detail


def test_kuke006_flags_call_mediated_cross_class_cycle(tmp_path):
    pkg = _mini_repo(tmp_path, {"runtime/pair.py": '''
        import threading


        class Eng:
            def __init__(self):
                self._lock = threading.Lock()
                self.reg = Reg()

            def poke(self):
                with self._lock:
                    self.reg.bump()

            def kick(self):
                with self._lock:
                    pass


        class Reg:
            def __init__(self):
                self._lock = threading.Lock()
                self.eng = Eng()

            def bump(self):
                with self._lock:
                    self.eng.kick()
    '''})
    found = run_analysis(pkg, select=["KUKE006"])
    assert _rules(found) == ["KUKE006"]


def test_kuke006_silent_on_consistent_order(tmp_path):
    pkg = _mini_repo(tmp_path, {"runtime/thing.py": '''
        import threading


        class C:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def one(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def two(self):
                with self._a_lock:
                    with self._b_lock:
                        pass
    '''})
    assert run_analysis(pkg, select=["KUKE006"]) == []


def test_kuke005_recognizes_sanitize_factory_locks(tmp_path):
    """Locks created through the kukesan factory (sanitize.lock) are
    first-class lock attributes for the static pass too."""
    pkg = _mini_repo(tmp_path, {"runtime/thing.py": '''
        from kukeon_tpu import sanitize


        class Engine:
            def __init__(self):
                self._mtx = sanitize.lock("Engine._mtx")
                self.depth = 0

            def locked_bump(self):
                with self._mtx:
                    self.depth += 1

            def racy(self):
                self.depth = 5
    '''})
    found = run_analysis(pkg, select=["KUKE005"])
    assert [(f.rule, f.detail) for f in found] == [("KUKE005", "depth")]


def test_kuke005_guarded_by_annotation_declares_contract(tmp_path):
    """An explicit ``# guarded-by:`` comment binds the attribute even when
    no locked write exists for inference to learn from — the declared
    attr's unlocked writes become findings."""
    pkg = _mini_repo(tmp_path, {"runtime/thing.py": '''
        import threading


        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self.beat = 0.0   # guarded-by: _lock

            def racy(self):
                self.beat = 1.0

            def fine(self):
                with self._lock:
                    self.beat = 2.0
    '''})
    found = run_analysis(pkg, select=["KUKE005"])
    assert [(f.scope, f.detail) for f in found] == [("Engine.racy", "beat")]
    assert "guarded-by" in found[0].message


# --- KUKE009: sub-10ms sleep-polling loops -----------------------------------


def test_kuke009_flags_sub10ms_sleep_loop(tmp_path):
    pkg = _mini_repo(tmp_path, {"serving/engine.py": '''
        import time

        TICK = 0.002


        class Engine:
            def _loop(self):
                while self._running:
                    if not self.step():
                        time.sleep(0.001)

            def _loop2(self):
                for _ in range(10):
                    time.sleep(TICK)   # module constant resolves too
    '''})
    found = run_analysis(pkg, select=["KUKE009"])
    assert sorted(f.detail for f in found) == ["sleep:0.001", "sleep:0.002"]
    assert {f.scope for f in found} == {"Engine._loop", "Engine._loop2"}


def test_kuke009_allows_slow_polls_and_non_loop_sleeps(tmp_path):
    pkg = _mini_repo(tmp_path, {"serving/engine.py": '''
        import time


        class Engine:
            def drain(self):
                while self.busy():
                    time.sleep(0.05)      # >= 10ms poll: acceptable

            def pause(self):
                time.sleep(0.001)         # not in a loop

            def spawn(self):
                while True:
                    def later():
                        time.sleep(0.001)  # nested def: not loop-body work
                    self.submit(later)
                    break
    '''})
    assert run_analysis(pkg, select=["KUKE009"]) == []


# --- KUKE007: fault-point registry -------------------------------------------

FAULTS_FIXTURE = '''
    POINTS = (
        "a.b",
        "stale.point",
    )

    def maybe_fail(point):
        pass
'''


def test_kuke007_flags_undeclared_stale_and_dynamic(tmp_path):
    pkg = _mini_repo(tmp_path, {
        "faults.py": FAULTS_FIXTURE,
        "mod.py": '''
            from pkg import faults

            def f(name):
                faults.maybe_fail("a.b")        # declared: fine
                faults.maybe_fail("c.d")        # undeclared
                faults.maybe_fail(name)         # dynamic
        ''',
    })
    found = run_analysis(pkg, select=["KUKE007"])
    details = sorted(f.detail for f in found)
    assert details == ["<dynamic>", "c.d", "stale.point"]


def test_kuke007_silent_when_registry_matches(tmp_path):
    pkg = _mini_repo(tmp_path, {
        "faults.py": '''
            POINTS = ("a.b",)
        ''',
        "mod.py": '''
            from pkg import faults

            def f():
                faults.maybe_fail("a.b")
        ''',
    })
    assert run_analysis(pkg, select=["KUKE007"]) == []


# --- KUKE008: metric doc-drift -----------------------------------------------


def test_kuke008_flags_undocumented_metric(tmp_path):
    pkg = _mini_repo(tmp_path, {
        "mod.py": 'NAME = "kukeon_test_total"\n',
    }, readme="# metrics\n\nnothing here\n")
    found = run_analysis(pkg, select=["KUKE008"])
    assert _rules(found) == ["KUKE008"]
    assert found[0].detail == "kukeon_test_total"


def test_kuke008_silent_when_documented(tmp_path):
    pkg = _mini_repo(tmp_path, {
        "mod.py": 'NAME = "kukeon_test_total"\n',
    }, readme="| `kukeon_test_total` | counter | test |\n")
    assert run_analysis(pkg, select=["KUKE008"]) == []


# --- KUKE010: span phase registry --------------------------------------------

PHASES_FIXTURE = '''
    PHASES = (
        "admitted",
        "stale_phase",
    )
'''


def test_kuke010_flags_undeclared_stale_and_dynamic(tmp_path):
    pkg = _mini_repo(tmp_path, {
        "obs/trace.py": PHASES_FIXTURE,
        "mod.py": '''
            from pkg import sanitize

            def f(span, name):
                span.event("admitted")          # declared: fine
                span.event("mystery_phase")     # undeclared
                span.event(name)                # dynamic
                halt = sanitize.event("Cls._halt")   # Event factory: exempt
        ''',
    })
    found = run_analysis(pkg, select=["KUKE010"])
    details = sorted(f.detail for f in found)
    assert details == ["<dynamic>", "mystery_phase", "stale_phase"]


def test_kuke010_silent_when_registry_matches(tmp_path):
    pkg = _mini_repo(tmp_path, {
        "obs/trace.py": '''
            PHASES = ("admitted",)
        ''',
        "mod.py": '''
            def f(span):
                span.event("admitted")
        ''',
    })
    assert run_analysis(pkg, select=["KUKE010"]) == []


def test_kuke010_silent_without_a_trace_module(tmp_path):
    # Fixture repos with no obs/trace.py must not be forced to declare a
    # registry just because something has an .event method.
    pkg = _mini_repo(tmp_path, {
        "mod.py": '''
            def f(span):
                span.event("whatever")
        ''',
    })
    assert run_analysis(pkg, select=["KUKE010"]) == []


# --- KUKE011: alert rules vs the metric registry -----------------------------


ALERTS_FIXTURE = '''
    BUILTIN_RULES = (
        Rule(name="Good", expr="kukeon_known_total", agg="max",
             window_s=60, op=">", threshold=1),
        Rule(name="Dead", expr="kukeon_missing_total", agg="max",
             window_s=60, op=">", threshold=1),
        Rule(name="Dyn", expr=_BUILT_AT_IMPORT, agg="max",
             window_s=60, op=">", threshold=1),
        Rule(name="Ratio",
             expr="kukeon_known_total{cell=x} / kukeon_also_missing",
             agg="max", window_s=60, op=">", threshold=1),
    )
'''


def test_kuke011_flags_undeclared_and_dynamic_rule_families(tmp_path):
    pkg = _mini_repo(tmp_path, {
        "obs/alerts.py": ALERTS_FIXTURE,
        # The declared registry lives OUTSIDE the alerts module — a
        # rule's own expr literal must never satisfy itself ("Dead"
        # references kukeon_missing_total as a plain literal and is
        # still a finding).
        "serving/metrics.py": 'FAMS = ("kukeon_known_total",)\n',
    })
    found = run_analysis(pkg, select=["KUKE011"])
    assert sorted(f.detail for f in found) == [
        "<dynamic>", "kukeon_also_missing", "kukeon_missing_total"]
    by_detail = {f.detail: f for f in found}
    assert by_detail["kukeon_missing_total"].scope == "Dead"
    assert by_detail["<dynamic>"].scope == "Dyn"
    assert by_detail["kukeon_also_missing"].scope == "Ratio"
    assert all(f.file.endswith("obs/alerts.py") for f in found)


def test_kuke011_silent_when_families_are_declared(tmp_path):
    pkg = _mini_repo(tmp_path, {
        "obs/alerts.py": '''
            BUILTIN_RULES = (
                Rule(name="A", expr="kukeon_a_total{cell=x}", agg="max",
                     window_s=60, op=">", threshold=1),
                Rule(name="B", expr="kukeon_a_total / kukeon_b", agg="avg",
                     window_s=60, op="<", threshold=1),
            )
        ''',
        "serving/metrics.py":
            'FAMS = ("kukeon_a_total", "kukeon_b")\n',
    })
    assert run_analysis(pkg, select=["KUKE011"]) == []


def test_kuke011_silent_without_an_alerts_module(tmp_path):
    pkg = _mini_repo(tmp_path, {
        "mod.py": 'FAMS = ("kukeon_a_total",)\n',
    })
    assert run_analysis(pkg, select=["KUKE011"]) == []


# --- KUKE012: KV handoff transfer discipline ---------------------------------


def test_kuke012_flags_raw_transfers_in_handoff_code(tmp_path):
    pkg = _engine_repo(tmp_path, '''
        def _finish_export(self, kv):
            block = self._insert(self.state, kv, 4, 0, 1)
            host = np.asarray(block)            # raw readback of KV bytes
            jax.device_get(block)
            return host

        def _dispatch_import(self, block):
            up = jnp.asarray(block)             # raw upload of KV bytes
            dev = jax.device_put(block)
            return up, dev

        def step(self):
            # Not handoff-named: KUKE012 stays out (KUKE001/002's scope).
            return jax.device_put([1])
    ''')
    found = run_analysis(pkg, select=["KUKE012"])
    assert sorted(f.detail for f in found) == [
        "jax.device_get", "jax.device_put", "jnp.asarray", "np.asarray"]
    assert all(f.rule == "KUKE012" for f in found)
    scopes = {f.scope for f in found}
    assert scopes == {"ServingEngine._finish_export",
                      "ServingEngine._dispatch_import"}


def test_kuke012_silent_through_the_counted_seams(tmp_path):
    pkg = _engine_repo(tmp_path, '''
        def _finish_export(self, kv):
            block = self._insert(self.state, kv, 4, 0, 1)
            return self._fetch(block)           # the seam: counted

        def _dispatch_import(self, block):
            padded = np.zeros((2, 1, 8), np.float32)   # host work: fine
            return self._upload(padded)         # the seam: counted
    ''')
    assert run_analysis(pkg, select=["KUKE012"]) == []


def test_kuke012_covers_serving_cell_kv_helpers(tmp_path):
    pkg = _mini_repo(tmp_path, {"runtime/serving_cell.py": '''
        import jax
        import numpy as np


        def pack_kv(header, k, v):
            return jax.device_get(k)            # handoff bytes, raw seam


        def unrelated(x):
            return jax.device_get(x)            # not handoff-named: silent
    '''})
    found = run_analysis(pkg, select=["KUKE012"])
    assert [f.detail for f in found] == ["jax.device_get"]
    assert found[0].scope == "pack_kv"


# --- KUKE013: control-plane boot imports -------------------------------------


def _runtime_repo(tmp_path, files: dict[str, str]):
    """Like _mini_repo but the package dir is literally ``kukeon_tpu`` —
    KUKE013 scopes by the real control-plane path (kukeon_tpu/runtime/),
    so the fixture tree must carry the same prefix."""
    tmp_path.mkdir(parents=True, exist_ok=True)
    (tmp_path / "README.md").write_text("docs\n")
    pkg = tmp_path / "kukeon_tpu"
    for rel, body in files.items():
        path = pkg / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(body))
    return str(pkg)


def test_kuke013_flags_heavy_module_and_class_scope_imports(tmp_path):
    pkg = _runtime_repo(tmp_path, {"runtime/daemon.py": '''
        import os                                  # light: fine
        import jax                                 # heavy, module scope
        from kukeon_tpu.models import llama        # heavy, module scope

        class RPCService:
            import jax.numpy as jnp                # class body runs at import

            def handler(self):
                from kukeon_tpu import serving     # lazy: the fix, silent
                return serving
    '''})
    found = run_analysis(pkg, select=["KUKE013"])
    assert sorted(f.detail for f in found) == [
        "import:jax", "import:jax.numpy", "import:kukeon_tpu.models"]
    assert all(f.rule == "KUKE013" for f in found)
    by_detail = {f.detail: f for f in found}
    assert by_detail["import:jax"].scope == "<module>"
    assert by_detail["import:jax.numpy"].scope == "RPCService"


def test_kuke013_from_package_binding_counts_as_heavy(tmp_path):
    # `from kukeon_tpu import serving` binds the whole heavy package just
    # as surely as `import kukeon_tpu.serving` does.
    pkg = _runtime_repo(tmp_path, {"runtime/cli.py": '''
        from kukeon_tpu import serving
    '''})
    found = run_analysis(pkg, select=["KUKE013"])
    assert [f.detail for f in found] == ["import:kukeon_tpu.serving"]


def test_kuke013_silent_on_lazy_imports_and_exempt_files(tmp_path):
    pkg = _runtime_repo(tmp_path, {
        # Control plane done right: heavy imports only inside functions.
        "runtime/scaler.py": '''
            import os
            import threading

            def tick():
                import jax
                from kukeon_tpu.serving import engine
                return jax, engine
        ''',
        # The data-plane process: heavy module-scope imports deliberate,
        # measured as the boot_imports cold-start phase.
        "runtime/serving_cell.py": '''
            import jax
            from kukeon_tpu.models import llama
        ''',
        # Outside the control plane: KUKE001/002's territory, not ours.
        "serving/engine.py": '''
            import jax
        ''',
    })
    assert run_analysis(pkg, select=["KUKE013"]) == []


# --- baseline suppression ----------------------------------------------------


def test_baseline_round_trip(tmp_path):
    pkg = _mini_repo(tmp_path, {"runtime/thing.py": LOCKED_CLASS + '''
        def racy(self):
            self.depth = 5
    '''})
    found = run_analysis(pkg, select=["KUKE005"])
    assert len(found) == 1

    # Baseline the finding: the same tree now reports clean.
    bl_path = str(tmp_path / "baseline.json")
    Baseline([BaselineEntry(found[0].fingerprint,
                            "intentional: fixture")]).save(bl_path)
    new, suppressed, stale = Baseline.load(bl_path).apply(found)
    assert (len(new), len(suppressed), len(stale)) == (0, 1, 0)

    # The justification survives the file round trip.
    with open(bl_path) as f:
        data = json.load(f)
    assert data["suppressions"][0]["justification"] == "intentional: fixture"

    # A *new* violation is NOT suppressed by the existing entry — while
    # the baselined one stays suppressed (fingerprints are scope-level,
    # line-independent).
    pkg2 = _mini_repo(tmp_path / "v2", {
        "runtime/thing.py": LOCKED_CLASS + '''
        def racy(self):
            self.depth = 5

        def racy2(self):
            self.depth = 6
        '''})
    found2 = run_analysis(pkg2, select=["KUKE005"])
    new2, suppressed2, _stale2 = Baseline.load(bl_path).apply(found2)
    assert [f.scope for f in new2] == ["Engine.racy2"]
    assert [f.scope for f in suppressed2] == ["Engine.racy"]

    # Fixing the violation leaves the entry stale — visibly.
    pkg3 = _mini_repo(tmp_path / "v3", {
        "runtime/thing.py": LOCKED_CLASS})
    new3, suppressed3, stale3 = Baseline.load(bl_path).apply(
        run_analysis(pkg3, select=["KUKE005"]))
    assert (new3, suppressed3) == ([], [])
    assert len(stale3) == 1


def test_cli_baseline_modes(tmp_path, capsys):
    pkg = _mini_repo(tmp_path, {"runtime/thing.py": LOCKED_CLASS + '''
        def racy(self):
            self.depth = 5
    '''})
    bl = str(tmp_path / "bl.json")

    # New finding, no baseline: exit 1.
    assert kukelint_main([pkg, "--baseline", bl,
                          "--select", "KUKE005"]) == 1
    # --update-baseline captures it; the run is then clean.
    assert kukelint_main([pkg, "--baseline", bl, "--select", "KUKE005",
                          "--update-baseline"]) == 0
    assert kukelint_main([pkg, "--baseline", bl,
                          "--select", "KUKE005"]) == 0
    # Fix the violation: stale entry passes by default, fails strict mode.
    pkg_fixed = _mini_repo(tmp_path / "fixed",
                           {"runtime/thing.py": LOCKED_CLASS})
    assert kukelint_main([pkg_fixed, "--baseline", bl,
                          "--select", "KUKE005"]) == 0
    assert kukelint_main([pkg_fixed, "--baseline", bl, "--select", "KUKE005",
                          "--strict-baseline"]) == 1
    capsys.readouterr()


# --- the real tree (tier-1 acceptance) ---------------------------------------


def test_all_rules_are_registered():
    assert registered_rules() == (
        "KUKE001", "KUKE002", "KUKE003", "KUKE004",
        "KUKE005", "KUKE006", "KUKE007", "KUKE008", "KUKE009",
        "KUKE010", "KUKE011", "KUKE012", "KUKE013", "KUKE014",
        "KUKE015",
    )


# --- structured output (--format json|github) --------------------------------


def test_cli_format_json(tmp_path, capsys):
    pkg = _mini_repo(tmp_path, {"runtime/thing.py": LOCKED_CLASS + '''
        def racy(self):
            self.depth = 5
    '''})
    bl = str(tmp_path / "bl.json")
    assert kukelint_main([pkg, "--baseline", bl, "--select", "KUKE005",
                          "--format", "json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["tool"] == "kukelint"
    (f,) = doc["findings"]
    assert f["rule"] == "KUKE005"
    assert f["file"].endswith("runtime/thing.py")
    assert f["line"] > 0 and f["scope"] == "Engine.racy"
    # The stable id IS the baseline fingerprint: line-independent.
    assert f["id"].startswith("KUKE005:")
    assert f["id"].endswith(":Engine.racy:depth")
    assert doc["stale_baseline_entries"] == []

    # A clean tree emits an empty findings list, exit 0.
    pkg_ok = _mini_repo(tmp_path / "ok", {"runtime/thing.py": LOCKED_CLASS})
    assert kukelint_main([pkg_ok, "--baseline", bl, "--select", "KUKE005",
                          "--format", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["findings"] == []


def test_cli_format_github(tmp_path, capsys):
    pkg = _mini_repo(tmp_path, {"runtime/thing.py": LOCKED_CLASS + '''
        def racy(self):
            self.depth = 5
    '''})
    assert kukelint_main([pkg, "--baseline", str(tmp_path / "bl.json"),
                          "--select", "KUKE005",
                          "--format", "github"]) == 1
    out = capsys.readouterr().out
    assert out.startswith("::error file=")
    assert ",line=" in out and "title=KUKE005::" in out


# --- guarded-by contract export ----------------------------------------------


def test_write_contracts_cli_and_shape(tmp_path, capsys):
    pkg = _mini_repo(tmp_path, {"runtime/thing.py": LOCKED_CLASS + '''
        def annotated(self):
            with self._lock:
                self.extra = 1   # guarded-by: _lock
    '''})
    out_path = str(tmp_path / "guarded_by.json")
    assert kukelint_main([pkg, "--write-contracts", out_path]) == 0
    capsys.readouterr()
    with open(out_path) as f:
        doc = json.load(f)
    assert doc["version"] == 1
    # The mini repo's package dir is "pkg": dotted module keys.
    entry = doc["classes"]["pkg.runtime.thing.Engine"]
    assert entry["depth"] == ["_lock"]
    assert entry["extra"] == ["_lock"]


def test_checked_in_contract_matches_the_tree():
    """Drift guard: analysis/guarded_by.json must equal what
    --write-contracts would regenerate from today's sources — the runtime
    sanitizer enforces this file, so it must never go stale."""
    from kukeon_tpu.analysis import (
        default_contracts_path, guarded_contracts, load_sources,
        render_contracts,
    )

    want = render_contracts(guarded_contracts(load_sources(PKG_ROOT),
                                              PKG_ROOT))
    with open(default_contracts_path()) as f:
        assert f.read() == want, (
            "analysis/guarded_by.json is stale — regenerate with "
            "`python -m kukeon_tpu.analysis --write-contracts`")


def test_contract_covers_engine_and_lifecycle():
    """The real tree's contract names the invariants kukesan enforces in
    the sanitized tier-1 run (spot anchor, not exhaustive)."""
    from kukeon_tpu.analysis import default_contracts_path

    with open(default_contracts_path()) as f:
        classes = json.load(f)["classes"]
    eng = classes["kukeon_tpu.serving.engine.ServingEngine"]
    assert eng["last_progress"] == ["_lock"]
    assert eng["_running"] == ["_lock"]
    mix = classes["kukeon_tpu.runtime.serving_cell.LifecycleMixin"]
    assert mix["draining"] == ["_drain_lock"]


def test_analyzer_package_passes_its_own_lint():
    """Self-check: the analyzer (as part of the package scan) and the whole
    tree report nothing beyond the checked-in baseline. This is the tier-1
    enforcement of every invariant kukelint covers: a new raw transfer,
    unstable jit call, unlocked write, lock cycle, undeclared fault point,
    or undocumented metric fails HERE with file:line."""
    findings = run_analysis(PKG_ROOT)
    baseline = Baseline.load(os.path.join(PKG_ROOT, "analysis",
                                          "baseline.json"))
    new, _suppressed, stale = baseline.apply(findings)
    assert new == [], "\n".join(f.render() for f in new)
    assert stale == [], [e.fingerprint for e in stale]


def test_cli_runs_clean_on_the_real_package():
    proc = subprocess.run(
        [sys.executable, "-m", "kukeon_tpu.analysis", "--strict-baseline"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "finding(s)" in proc.stdout


# --- mypy gate (skip-if-unavailable) -----------------------------------------


def test_mypy_strict_modules_typecheck():
    """The strictly-annotated modules (pyproject [tool.mypy] overrides:
    obs/registry.py, serving/kv_pages.py, gateway/router.py, and the
    sanitize package) pass mypy. Skips cleanly where mypy is not
    installed — the container does not bake it."""
    pytest.importorskip("mypy")
    proc = subprocess.run(
        [sys.executable, "-m", "mypy",
         "kukeon_tpu/obs/registry.py", "kukeon_tpu/serving/kv_pages.py",
         "kukeon_tpu/gateway/router.py", "kukeon_tpu/sanitize"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
