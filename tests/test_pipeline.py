"""Pipeline parallelism (GPipe over the ``pipe`` mesh axis): numerics parity
with the plain forward, composition with data parallelism, and the
differentiable train step."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from kukeon_tpu.models import llama
from kukeon_tpu.parallel import make_mesh, set_mesh
from kukeon_tpu.parallel.pipeline import (
    make_pp_train_step,
    pipeline_forward,
    pp_specs_for_params,
)


@pytest.fixture(scope="module")
def model4():
    cfg = dataclasses.replace(llama.llama_tiny(), num_layers=4)
    params = llama.init_params(jax.random.key(0), cfg)
    return cfg, params


def _shard_pp(params, mesh):
    specs = pp_specs_for_params(params)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs, is_leaf=lambda v: isinstance(v, P),
    )


def test_pipeline_matches_plain_forward(model4):
    """pipe=4 x data=2 pipeline forward == unsharded llama.forward."""
    cfg, params = model4
    B, S = 4, 16
    tokens = jax.random.randint(jax.random.key(1), (B, S), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
    want, _ = llama.forward(params, cfg, tokens, positions)

    mesh = make_mesh(pipe=4, data=2)
    sharded = _shard_pp(params, mesh)
    with set_mesh(mesh):
        got = jax.jit(
            lambda p, t, pos: pipeline_forward(
                p, cfg, t, pos, mesh=mesh, num_microbatches=4
            )
        )(sharded, tokens, positions)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_single_stage_degenerates(model4):
    """pipe=1 must equal the plain forward exactly (no schedule effects)."""
    cfg, params = model4
    B, S = 2, 8
    tokens = jax.random.randint(jax.random.key(2), (B, S), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
    want, _ = llama.forward(params, cfg, tokens, positions)

    mesh = make_mesh(pipe=1, data=8)
    sharded = _shard_pp(params, mesh)
    with set_mesh(mesh):
        got = jax.jit(
            lambda p, t, pos: pipeline_forward(
                p, cfg, t, pos, mesh=mesh, num_microbatches=2
            )
        )(sharded, tokens, positions)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_validations(model4):
    cfg, params = model4
    mesh = make_mesh(pipe=4, data=2)
    tokens = jnp.zeros((4, 8), jnp.int32)
    positions = jnp.zeros((4, 8), jnp.int32)
    with set_mesh(mesh):
        with pytest.raises(ValueError, match="microbatches"):
            pipeline_forward(params, cfg, tokens, positions, mesh=mesh,
                             num_microbatches=3)
        bad_cfg = dataclasses.replace(cfg, num_layers=3)
        with pytest.raises(ValueError, match="pipe"):
            pipeline_forward(params, bad_cfg, tokens, positions, mesh=mesh)


def test_pp_train_step_learns(model4):
    """Two pp train steps: loss finite and decreasing on a repeated batch
    (backward through the ppermute ring works)."""
    import optax

    from kukeon_tpu.training import create_train_state
    from kukeon_tpu.training.train_step import make_optimizer

    cfg, _ = model4
    mesh = make_mesh(pipe=4, data=2)
    with set_mesh(mesh):
        optimizer = make_optimizer(learning_rate=1e-2, warmup_steps=1,
                                   total_steps=10)
        state, optimizer = create_train_state(
            cfg, mesh, jax.random.key(0), optimizer,
            init_fn=lambda k: llama.init_params(k, cfg),
            specs=pp_specs_for_params(
                jax.eval_shape(lambda k: llama.init_params(k, cfg),
                               jax.random.key(0))
            ),
        )
        step = make_pp_train_step(cfg, mesh, optimizer, num_microbatches=4)
        B, S = 8, 16
        tokens = jax.random.randint(jax.random.key(3), (B, S), 0, cfg.vocab_size)
        targets = jnp.roll(tokens, -1, axis=1)
        mask = jnp.ones((B, S), jnp.float32)
        state, loss0 = step(state, tokens, targets, mask)
        state, _ = step(state, tokens, targets, mask)   # warmup step: lr ~ 0
        state, loss2 = step(state, tokens, targets, mask)
    assert np.isfinite(float(loss0)) and np.isfinite(float(loss2))
    assert float(loss2) < float(loss0)
    assert int(state.step) == 3
