"""UnixClient transient-dial retry: CLI calls during a daemon restart see
ENOENT (socket not yet created) or ECONNREFUSED (listener not yet accepting)
for a moment — the client must ride that out within its budget instead of
hard-failing, and still fail promptly once the budget is spent."""

import json
import os
import socket
import threading
import time

import pytest

from kukeon_tpu.runtime.client import UnixClient
from kukeon_tpu.runtime.errors import Unavailable


def _serve_one(path: str, delay_s: float):
    """After ``delay_s``, bind a one-shot JSON-RPC line server at ``path``."""

    def run():
        time.sleep(delay_s)
        srv = socket.socket(socket.AF_UNIX)
        srv.bind(path)
        srv.listen(1)
        conn, _ = srv.accept()
        f = conn.makefile("rwb")
        req = json.loads(f.readline())
        f.write((json.dumps({"id": req["id"], "result": {"pong": True}})
                 + "\n").encode())
        f.flush()
        conn.close()
        srv.close()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def test_dial_rides_out_daemon_restart_window(tmp_path):
    path = str(tmp_path / "kukeond.sock")
    assert not os.path.exists(path)          # ENOENT at first dial attempts
    t = _serve_one(path, delay_s=0.4)
    c = UnixClient(path, retry_budget_s=3.0)
    try:
        assert c.call("Ping") == {"pong": True}
    finally:
        c.close()
        t.join(timeout=5)


def test_dial_fails_promptly_past_budget(tmp_path):
    path = str(tmp_path / "never.sock")
    c = UnixClient(path, retry_budget_s=0.3)
    t0 = time.monotonic()
    with pytest.raises(Unavailable, match="is the daemon running"):
        c.call("Ping")
    elapsed = time.monotonic() - t0
    assert 0.25 <= elapsed < 3.0             # retried through, then gave up


def test_connection_refused_is_retried(tmp_path):
    """A bound-but-dead socket file (daemon crashed) refuses connections;
    a listener taking over inside the budget gets the call."""
    path = str(tmp_path / "stale.sock")
    dead = socket.socket(socket.AF_UNIX)
    dead.bind(path)
    dead.close()                             # file exists, nobody listens

    def takeover():
        time.sleep(0.3)
        os.unlink(path)
        srv = socket.socket(socket.AF_UNIX)
        srv.bind(path)
        srv.listen(1)
        conn, _ = srv.accept()
        f = conn.makefile("rwb")
        req = json.loads(f.readline())
        f.write((json.dumps({"id": req["id"], "result": "ok"}) + "\n").encode())
        f.flush()
        conn.close()
        srv.close()

    t = threading.Thread(target=takeover, daemon=True)
    t.start()
    c = UnixClient(path, retry_budget_s=3.0)
    try:
        assert c.call("Ping") == "ok"
    finally:
        c.close()
        t.join(timeout=5)
