"""Pre-warmed standby rollouts (PR 14 fleet half): the runner's parked
replica start/stop primitives, the rolling restart's standby pre-warm
(census held at N through every restart window), the RolloutCell RPC +
CLI plumbing, and the scaler's pending-rule pre-warm.

Same philosophy as the gateway/scaler suites: replica behavior is
scripted FakeReplica HTTP, the container half is the fake backend, and
the state machine under test is the production one end to end."""

from __future__ import annotations

import argparse
import threading
import time
import urllib.request

import pytest

from kukeon_tpu.gateway.rollout import (
    RolloutError, RolloutStep, StandbyStep, rolling_restart,
)
from kukeon_tpu.runtime import scaler as scaler_mod
from kukeon_tpu.runtime.errors import FailedPrecondition

from test_gateway import FakeReplica, _free_port_block
from test_scaler import _autoscaled_doc, _controller, _scaler_rig


# --- runner: the parked start/stop primitives --------------------------------


def test_start_parked_replica_boots_without_raising_target(tmp_path):
    ctl, backend, store = _controller(tmp_path)
    ctl.create_cell(_autoscaled_doc(9300))
    runner = ctl.runner

    rec, cname = runner.start_parked_replica("default", "default", "default",
                                             "llm")
    assert cname == "model-server-1"
    # The standby is UP, on its pre-partitioned chip grant...
    assert rec.status.container("model-server-1").state == "running"
    started = {c.spec.name: c for c in backend.started}
    assert started["model-server-1"].env["TPU_VISIBLE_DEVICES"] == "1"
    # ...but the active target is untouched: the scaler/gateway census,
    # phase derivation, everything still sees one active replica.
    assert rec.status.target_replicas is None
    assert runner.model_target(rec) == 1
    assert rec.status.phase == "ready"

    # Idempotent: a standby already running is adopted, not restarted.
    n_started = sum(1 for c in backend.started
                    if c.spec.name == "model-server-1")
    rec, cname2 = runner.start_parked_replica("default", "default",
                                              "default", "llm")
    assert cname2 == "model-server-1"
    assert sum(1 for c in backend.started
               if c.spec.name == "model-server-1") == n_started


def test_start_parked_replica_requires_parked_capacity(tmp_path):
    ctl, _backend, _store = _controller(tmp_path)
    ctl.create_cell(_autoscaled_doc(9300, mx=3))
    runner = ctl.runner
    runner.scale_model_cell("default", "default", "default", "llm", 3)
    with pytest.raises(FailedPrecondition, match="no parked replica"):
        runner.start_parked_replica("default", "default", "default", "llm")


def test_stop_parked_replica_parks_again_but_spares_promoted(tmp_path):
    ctl, backend, store = _controller(tmp_path)
    ctl.create_cell(_autoscaled_doc(9300))
    runner = ctl.runner

    runner.start_parked_replica("default", "default", "default", "llm")
    rec = runner.stop_parked_replica("default", "default", "default", "llm",
                                     "model-server-1")
    assert rec.status.container("model-server-1").state == "exited"
    assert rec.status.target_replicas is None      # never touched

    # Pre-warm again, then promote it: the scale-up adopts the warm
    # container in place (no second start), and parking the NAME is now a
    # silent no-op — the replica is live capacity, not a standby.
    runner.start_parked_replica("default", "default", "default", "llm")
    n_started = sum(1 for c in backend.started
                    if c.spec.name == "model-server-1")
    rec = runner.scale_model_cell("default", "default", "default", "llm", 2)
    assert sum(1 for c in backend.started
               if c.spec.name == "model-server-1") == n_started
    rec = runner.stop_parked_replica("default", "default", "default", "llm",
                                     "model-server-1")
    assert rec.status.container("model-server-1").state == "running"
    assert runner.model_target(rec) == 2


# --- rolling_restart with a standby ------------------------------------------


def _ready_count(urls: list[str]) -> int:
    n = 0
    for u in urls:
        try:
            with urllib.request.urlopen(u + "/readyz", timeout=0.5) as r:
                n += r.status == 200
        except Exception:  # noqa: BLE001 — down/draining = not ready
            pass
    return n


def test_rolling_restart_standby_holds_ready_census():
    """The acceptance invariant at the state-machine level: with a standby
    pre-warmed first, the number of /readyz-200 replicas never dips below
    N (=2) at any instant of a full two-replica rollout."""
    base = _free_port_block(3)
    replicas = {0: FakeReplica(port=base), 1: FakeReplica(port=base + 1)}
    standby: dict[str, FakeReplica | None] = {"r": None}
    parked = []

    def respawn(i):
        def _r():
            replicas[i].kill()           # drained fake freed its port
            replicas[i] = FakeReplica(port=base + i)
        return _r

    steps = [RolloutStep(name=f"model-server-{i}", url=replicas[i].url,
                         restart=respawn(i)) for i in range(2)]
    sb = StandbyStep(
        name="model-server-2", url=f"http://127.0.0.1:{base + 2}",
        start=lambda: standby.__setitem__(
            "r", FakeReplica(port=base + 2)),
        stop=lambda: (parked.append(True), standby["r"].kill()))

    urls = [f"http://127.0.0.1:{base + i}" for i in range(3)]
    census: list[int] = []
    stop = threading.Event()

    def monitor():
        while not stop.is_set():
            census.append(_ready_count(urls))
            time.sleep(0.02)

    th = threading.Thread(target=monitor)
    th.start()
    try:
        results = rolling_restart(steps, drain_timeout_s=10.0,
                                  ready_timeout_s=10.0, poll_s=0.05,
                                  standby=sb)
    finally:
        stop.set()
        th.join(timeout=10)
        for r in replicas.values():
            r.kill()
        if standby["r"] is not None:
            standby["r"].kill()

    assert [r["replica"] for r in results] == ["model-server-0",
                                               "model-server-1"]
    # Every step's record names the standby that covered its window.
    for r in results:
        assert r["standby"]["replica"] == "model-server-2"
        assert r["standby"]["readyS"] >= 0.0
    assert parked == [True]            # parked again on the way out
    assert census, "census monitor produced no samples"
    assert min(census) >= 2, f"ready census dipped to {min(census)}"


def test_standby_start_failure_aborts_before_any_drain():
    a, b = FakeReplica(), FakeReplica()
    steps = [RolloutStep(name="model-server-0", url=a.url,
                         restart=lambda: None),
             RolloutStep(name="model-server-1", url=b.url,
                         restart=lambda: None)]
    sb = StandbyStep(name="model-server-2", url="http://127.0.0.1:1",
                     start=lambda: (_ for _ in ()).throw(
                         RuntimeError("no parked capacity")),
                     stop=lambda: None)
    try:
        with pytest.raises(RolloutError, match="rollout not begun") as ei:
            rolling_restart(steps, drain_timeout_s=5.0, ready_timeout_s=5.0,
                            standby=sb)
        assert ei.value.results == [
            {"replica": "model-server-2", "standby": True,
             "error": "start failed: RuntimeError: no parked capacity"}]
        # No victim was drained: the fleet is exactly as it was.
        assert not a.draining and not b.draining
    finally:
        a.kill()
        b.kill()


def test_standby_never_ready_aborts_and_parks():
    a = FakeReplica()
    steps = [RolloutStep(name="model-server-0", url=a.url,
                         restart=lambda: None)]
    parked = []
    sb = StandbyStep(name="model-server-1", url="http://127.0.0.1:1",
                     start=lambda: None,      # "starts" but never listens
                     stop=lambda: parked.append(True))
    try:
        with pytest.raises(RolloutError,
                           match="did not become ready") as ei:
            rolling_restart(steps, drain_timeout_s=5.0, ready_timeout_s=0.4,
                            poll_s=0.05, standby=sb)
        assert ei.value.results[0]["standby"] is True
        assert parked == [True]        # best-effort park even on abort
        assert not a.draining
    finally:
        a.kill()


# --- RolloutCell RPC: the full plumbing --------------------------------------


def _rollout_rig(tmp_path, monkeypatch, doc_port, doc):
    """Controller + two live FakeReplicas + the respawning restart shim
    (the same pattern as the gateway flood test)."""
    from kukeon_tpu.runtime import daemon as dmod

    ctl, backend, store = _controller(tmp_path)
    ctl.create_cell(doc)
    replicas = {0: FakeReplica(port=doc_port + 1),
                1: FakeReplica(port=doc_port + 2)}
    real_restart = dmod._rollout_restart

    def restart_and_respawn(ctl_, rec, cname):
        i = int(cname.rsplit("-", 1)[1])
        replicas[i].kill()
        cdir = store.container_dir(rec.realm, rec.space, rec.stack,
                                   rec.name, cname)
        backend.exit(cdir, 0)
        real_restart(ctl_, rec, cname)
        replicas[i] = FakeReplica(port=doc_port + 1 + i)

    monkeypatch.setattr(dmod, "_rollout_restart", restart_and_respawn)
    return ctl, backend, store, dmod.RPCService(ctl), replicas


def test_rollout_cell_standby_prewarms_and_parks(tmp_path, monkeypatch):
    base = _free_port_block(4)
    ctl, backend, store, service, replicas = _rollout_rig(
        tmp_path, monkeypatch, base,
        _autoscaled_doc(base, replicas=2, mx=3))
    # The parked replica's HTTP face: the fake backend starts no real
    # process, so the standby's server rides separately like every
    # FakeReplica — listening before the RPC probes its /readyz.
    sb = FakeReplica(port=base + 3)
    try:
        out = service.RolloutCell("default", "default", "default", "llm",
                                  drainTimeoutS=15.0, readyTimeoutS=15.0)
    finally:
        sb.kill()
        for r in replicas.values():
            r.kill()

    assert "aborted" not in out
    assert [r["replica"] for r in out["replicas"]] == [
        "model-server-0", "model-server-1"]
    for r in out["replicas"]:
        assert r["standby"]["replica"] == "model-server-2"
    # The standby container really started — and was parked again.
    rec = store.read_cell("default", "default", "default", "llm")
    assert rec.status.container("model-server-2").state == "exited"
    assert ctl.runner.model_target(rec) == 2       # target never touched
    assert rec.status.container("model-server-0").restarts == 1
    assert rec.status.container("model-server-1").restarts == 1


def test_rollout_cell_standby_false_skips_prewarm(tmp_path, monkeypatch):
    base = _free_port_block(4)
    _ctl, backend, _store, service, replicas = _rollout_rig(
        tmp_path, monkeypatch, base,
        _autoscaled_doc(base, replicas=2, mx=3))
    try:
        out = service.RolloutCell("default", "default", "default", "llm",
                                  drainTimeoutS=15.0, readyTimeoutS=15.0,
                                  standby=False)
    finally:
        for r in replicas.values():
            r.kill()
    assert "aborted" not in out
    assert all("standby" not in r for r in out["replicas"])
    assert not any(c.spec.name == "model-server-2" for c in backend.started)


def test_rollout_cell_no_parked_capacity_rolls_without_standby(
        tmp_path, monkeypatch):
    """A plain replicated cell (no maxReplicas) has nothing to pre-warm:
    the default standby=True is a request, not a requirement — the rollout
    proceeds exactly as before the standby existed."""
    from kukeon_tpu.runtime.api import types as t

    base = _free_port_block(3)
    doc = t.Document(
        kind=t.KIND_CELL, metadata=t.Metadata(name="llm"),
        spec=t.CellSpec(model=t.ModelSpec(model="tiny", chips=1,
                                          replicas=2, port=base)))
    _ctl, _backend, _store, service, replicas = _rollout_rig(
        tmp_path, monkeypatch, base, doc)
    try:
        out = service.RolloutCell("default", "default", "default", "llm",
                                  drainTimeoutS=15.0, readyTimeoutS=15.0)
    finally:
        for r in replicas.values():
            r.kill()
    assert "aborted" not in out
    assert all("standby" not in r for r in out["replicas"])


# --- scaler pre-warm ---------------------------------------------------------


def test_scaler_prewarms_on_pending_before_the_scale_up(tmp_path,
                                                        monkeypatch):
    ctl, store, sc, clock, tick = _scaler_rig(tmp_path, monkeypatch)
    calls = []
    real_prewarm = scaler_mod._prewarm_replica

    def prewarm_and_count(ctl_, rec):
        calls.append(rec.name)
        real_prewarm(ctl_, rec)

    monkeypatch.setattr(scaler_mod, "_prewarm_replica", prewarm_and_count)

    # First breaching tick: the up rule is PENDING — no scale-up yet, but
    # the pre-warm already booted the next parked replica.
    assert tick(9.0) == []
    assert calls == ["llm"]
    rec = store.read_cell("default", "default", "default", "llm")
    assert ctl.runner.model_target(rec) == 1
    assert rec.status.container("model-server-1").state == "running"

    # The debounced scale-up then promotes the WARM standby in place.
    evs = tick(9.0)
    assert [(e["direction"], e["to"]) for e in evs] == [("up", 2)]
    rec = store.read_cell("default", "default", "default", "llm")
    assert ctl.runner.model_target(rec) == 2
    assert rec.status.container("model-server-1").state == "running"


def test_scaler_prewarm_disabled_by_env(tmp_path, monkeypatch):
    monkeypatch.setenv(scaler_mod.PREWARM_ENV, "0")
    ctl, store, sc, clock, tick = _scaler_rig(tmp_path, monkeypatch)
    assert sc.prewarm is False
    assert tick(9.0) == []             # pending, and nothing pre-warmed
    rec = store.read_cell("default", "default", "default", "llm")
    c = rec.status.container("model-server-1")
    assert c is None or c.state != "running"


def test_scaler_prewarm_failure_degrades_to_cold_promotion(tmp_path,
                                                           monkeypatch):
    ctl, store, sc, clock, tick = _scaler_rig(tmp_path, monkeypatch)
    monkeypatch.setattr(
        scaler_mod, "_prewarm_replica",
        lambda ctl_, rec: (_ for _ in ()).throw(RuntimeError("boom")))
    assert tick(9.0) == []             # the failed pre-warm is swallowed
    evs = tick(9.0)                    # ...and the scale-up still lands
    assert [(e["direction"], e["result"], e["to"]) for e in evs] == [
        ("up", "ok", 2)]


# --- CLI ---------------------------------------------------------------------


def test_cli_rollout_standby_flag_and_printing(monkeypatch, capsys):
    from kukeon_tpu.runtime import cli

    parser = cli.build_parser()
    assert parser.parse_args(["rollout", "llm"]).standby is True
    args = parser.parse_args(["rollout", "llm", "--no-standby"])
    assert args.standby is False

    seen = {}
    out = {"cell": "default/default/default/llm", "replicas": [
        {"replica": "model-server-0", "drained": True, "readyS": 0.2,
         "standby": {"replica": "model-server-2", "readyS": 1.5}},
        {"replica": "model-server-1", "drained": True, "readyS": 0.3,
         "standby": {"replica": "model-server-2", "readyS": 1.5}},
    ]}

    class _Client:
        def call(self, method, **params):
            assert method == "RolloutCell"
            seen.update(params)
            return out

    monkeypatch.setattr(cli, "_client", lambda a: _Client())
    args = argparse.Namespace(name="llm", json=False, realm=None, space=None,
                              stack=None, drain_timeout=5.0,
                              ready_timeout=5.0, standby=False)
    assert cli.cmd_rollout(args) == 0
    assert seen["standby"] is False
    text = capsys.readouterr().out
    assert "standby model-server-2: ready in 1.5s" in text
    assert "census held at N" in text

    # A standby that failed before any drain prints as its own FAILED row
    # (the record has no drain/ready fields to format).
    out2 = {"cell": "default/default/default/llm", "aborted": True,
            "error": "standby model-server-2 failed to start",
            "replicas": [{"replica": "model-server-2", "standby": True,
                          "error": "start failed: RuntimeError: boom"}]}

    class _Client2:
        def call(self, method, **params):
            return out2

    monkeypatch.setattr(cli, "_client", lambda a: _Client2())
    assert cli.cmd_rollout(args) == 1
    text = capsys.readouterr().out
    assert "standby model-server-2: FAILED: start failed" in text
