"""Paged KV cache: allocator bookkeeping, paged-engine parity, refcounted
prefix sharing, preemption/requeue ordering, and kv.alloc exhaustion
shedding (ISSUE 6 tentpole + satellites)."""

import os
import threading
import time

import jax
import numpy as np
import pytest

from kukeon_tpu import faults
from kukeon_tpu.models import llama
from kukeon_tpu.parallel import make_mesh
from kukeon_tpu.serving import (
    PageAllocator,
    PagePoolExhausted,
    RejectedError,
    SamplingParams,
    ServingEngine,
)
from kukeon_tpu.serving.kv_pages import SCRATCH_PAGE, pages_for


# --- allocator bookkeeping ---------------------------------------------------


class TestPageAllocator:
    def test_alloc_free_roundtrip(self):
        a = PageAllocator(8, 16)
        assert a.free == 8 and a.in_use == 0
        pages = a.alloc(3)
        assert len(pages) == 3 and len(set(pages)) == 3
        assert SCRATCH_PAGE not in pages          # page 0 is never issued
        assert a.free == 5 and a.in_use == 3
        assert all(a.refcount(p) == 1 for p in pages)
        assert a.unref(pages) == 3
        assert a.free == 8 and a.in_use == 0

    def test_refcounted_sharing(self):
        a = PageAllocator(4, 8)
        pages = a.alloc(2)
        a.ref(pages)                              # a second reader
        assert all(a.refcount(p) == 2 for p in pages)
        assert a.unref(pages) == 0                # first drop frees nothing
        assert a.free == 2
        assert a.unref(pages) == 2                # second drop frees both
        assert a.free == 4

    def test_exhaustion_is_all_or_nothing(self):
        a = PageAllocator(4, 8)
        a.alloc(3)
        with pytest.raises(PagePoolExhausted):
            a.alloc(2)
        assert a.free == 1                        # nothing was handed out

    def test_freed_pages_reissue_fifo(self):
        """A just-freed page is re-issued as late as possible (defense in
        depth under the double-buffered decode dispatch)."""
        a = PageAllocator(3, 8)
        first = a.alloc(2)
        a.unref([first[0]])
        # first[0] went to the BACK of the free list: the untouched page
        # is issued before it.
        assert a.alloc(1)[0] != first[0]

    def test_ref_unref_unallocated_fail_loudly(self):
        a = PageAllocator(2, 8)
        with pytest.raises(ValueError):
            a.ref([1])
        with pytest.raises(ValueError):
            a.unref([2])
        # Scratch is silently skipped (block tables are padded with it).
        a.ref([SCRATCH_PAGE])
        a.unref([SCRATCH_PAGE])

    def test_pages_for(self):
        assert pages_for(0, 16) == 0
        assert pages_for(1, 16) == 1
        assert pages_for(16, 16) == 1
        assert pages_for(17, 16) == 2
        assert PageAllocator(4, 16).pages_for(33) == 3


# --- paged engine ------------------------------------------------------------


def _make(cfg=None, **kw):
    cfg = cfg or llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 128)
    kw.setdefault("decode_chunk", 4)
    return ServingEngine(cfg, params, mesh, **kw), cfg, params


def test_paged_greedy_matches_legacy():
    """The paged gather/scatter programs are a pure layout change: greedy
    output is token-identical to the legacy contiguous engine."""
    eng_p, cfg, params = _make(kv_page_tokens=16, kv_pool_pages=16)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    eng_l = ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=128,
                          decode_chunk=4)
    prompt = np.arange(1, 20, dtype=np.int32)
    sp = SamplingParams(max_new_tokens=8, temperature=0.0)
    assert eng_p.generate(prompt, sp) == eng_l.generate(prompt, sp)
    # Pages free page-granularly as the request finishes.
    assert eng_p._pool.in_use == 0


def test_paged_page_size_must_tile():
    cfg = llama.llama_tiny()
    with pytest.raises(ValueError, match="max_seq_len"):
        _make(cfg, kv_page_tokens=48)             # 128 % 48 != 0
    with pytest.raises(ValueError, match="bucket"):
        _make(cfg, kv_page_tokens=32, prefill_buckets=(48, 128))


def test_paged_overlong_prompt_fails_at_submit():
    eng, *_ = _make(kv_page_tokens=16, kv_pool_pages=4)
    with pytest.raises(ValueError, match="pool"):
        eng.submit(np.ones((100,), np.int32))     # needs 7 pages, pool holds 4


def test_prefix_pages_shared_not_copied():
    """N sessions on one agent prefix pay its KV cost once: the second
    session references the stored pages (refcount), gathers them for a
    suffix-only prefill, and produces the same tokens a cold prefill
    would."""
    eng, cfg, params = _make(num_slots=4, kv_page_tokens=16,
                             kv_pool_pages=32)
    prefix = np.arange(1, 65, dtype=np.int32)     # 4 full pages
    sp = SamplingParams(max_new_tokens=4, temperature=0.0)

    r1 = eng.submit(np.concatenate([prefix, np.array([70, 71], np.int32)]),
                    sp, prefix_id="agent")
    while not r1.done.is_set():
        eng.step()
    assert eng.prefix_misses == 1
    entry = eng._prefix_cache["agent"]
    assert entry.length == 64 and len(entry.pages) == 4
    # The finished request released its references; the cache entry alone
    # pins the shared pages now.
    assert all(eng._pool.refcount(p) == 1 for p in entry.pages)
    assert eng._prefix_shared_pages() == 4.0

    r2 = eng.submit(np.concatenate([prefix, np.array([80, 81], np.int32)]),
                    sp, prefix_id="agent")
    while not r2.done.is_set():
        eng.step()
    assert eng.prefix_hits == 1

    # Cold-engine reference: same prompt, no prefix cache.
    eng2, *_ = _make(num_slots=4, kv_page_tokens=16, kv_pool_pages=32)
    assert r2.generated == eng2.generate(
        np.concatenate([prefix, np.array([80, 81], np.int32)]), sp)

    # A hit must NOT re-point the entry at the hitting session's prompt
    # (that would fold its private tail into the shared entry).
    assert eng._prefix_cache["agent"].length == 64


def test_preemption_under_pressure_completes_everything():
    """A pool too small for every in-flight context forces preemption; all
    requests still finish with their full token budget, the preemption
    counter moves, the victim's trace records a ``preempted`` phase, and
    the pool drains to zero."""
    eng, *_ = _make(num_slots=3, kv_page_tokens=16, kv_pool_pages=8,
                    prefix_cache_size=0)
    sp = SamplingParams(max_new_tokens=40, temperature=0.8)
    reqs = [eng.submit(np.arange(1, 40, dtype=np.int32), sp)
            for _ in range(3)]
    n = 0
    while not all(r.done.is_set() for r in reqs) and n < 800:
        eng.step()
        n += 1
    assert all(r.done.is_set() for r in reqs)
    assert all(r.error is None for r in reqs)
    assert all(len(r.generated) == 40 for r in reqs)
    assert int(eng._m_preempt.value(reason="kv_pressure")) >= 1
    victims = [r for r in reqs if r.preemptions > 0]
    assert victims
    for r in victims:
        assert "preempted" in [name for name, _t in r.trace.events]
    assert eng._pool.in_use == 0


def test_preempted_request_resumes_before_new_admissions():
    """Requeue ordering (ISSUE 6 satellite): a preempted request re-enters
    the queue AHEAD of requests that were admitted after it."""
    eng, *_ = _make(num_slots=2, kv_page_tokens=16, kv_pool_pages=6,
                    prefill_buckets=(64,), prefix_cache_size=0)
    sp = SamplingParams(max_new_tokens=48, temperature=0.5)
    # Two long-growing requests: their combined final footprint (2 * 4+
    # pages) overflows the 6-page pool, so the later-submitted one is
    # preempted when the first grows.
    a = eng.submit(np.arange(1, 33, dtype=np.int32), sp)
    b = eng.submit(np.arange(1, 33, dtype=np.int32), sp)
    while not b.preemptions and not (a.done.is_set() and b.done.is_set()):
        eng.step()
    assert b.preemptions >= 1 and not b.done.is_set()
    assert b in eng._resume

    # A newcomer admitted while b waits for pages must not overtake it.
    c = eng.submit(np.arange(1, 9, dtype=np.int32),
                   SamplingParams(max_new_tokens=4))
    while not b.done.is_set():
        eng.step()
        if eng._slot_req.count(None) < 2 and c.slot >= 0:
            # c got a slot while b still waits -> ordering violated...
            assert b.slot >= 0 or b.done.is_set(), (
                "newly admitted request seated before the preempted one")
    while not c.done.is_set():
        eng.step()
    assert b.error is None and len(b.generated) == 48
    assert c.error is None


def test_preempted_request_respects_deadline_while_parked():
    """A preempted request parked for resume still observes its deadline:
    expiry produces the in-band timeout terminal, not a silent hang."""
    eng, *_ = _make(num_slots=2, kv_page_tokens=16, kv_pool_pages=6,
                    prefill_buckets=(64,), prefix_cache_size=0)
    sp = SamplingParams(max_new_tokens=48, temperature=0.5)
    a = eng.submit(np.arange(1, 33, dtype=np.int32), sp)
    b = eng.submit(np.arange(1, 33, dtype=np.int32), sp,
                   deadline_s=30.0)
    while not b.preemptions and not (a.done.is_set() and b.done.is_set()):
        eng.step()
    assert b.preemptions >= 1 and not b.done.is_set()
    b.deadline = time.monotonic() - 0.001          # expire it in the park
    while not b.done.is_set():
        eng.step()
    assert b.timed_out
    assert isinstance(b.error, Exception)
    # a continues unharmed.
    while not a.done.is_set():
        eng.step()
    assert a.error is None and len(a.generated) == 48


# --- kv.alloc fault point ----------------------------------------------------


@pytest.mark.faults
def test_kv_alloc_exhaustion_sheds_never_deadlocks():
    """Injected allocator exhaustion (fault point kv.alloc) on an idle
    engine: nothing will ever free pages, so the request sheds with
    RejectedError + Retry-After — the emit channel gets its terminal
    event and nobody hangs."""
    eng, *_ = _make(kv_page_tokens=16, kv_pool_pages=16)
    os.environ[faults.ENV] = "kv.alloc:1"
    events = []
    req = eng.submit(np.arange(1, 9, dtype=np.int32),
                     SamplingParams(max_new_tokens=4),
                     emit=lambda t, d: events.append((t, d)))
    done = req.done.wait(0.01)
    assert not done
    for _ in range(10):
        eng.step()
        if req.done.is_set():
            break
    assert req.done.is_set()
    assert isinstance(req.error, RejectedError)
    assert req.error.retry_after_s > 0
    assert events[-1] == (-1, True)
    assert eng.shed_stats["kv_exhausted"] == 1

    # Disarm: the engine keeps serving normally afterwards.
    os.environ.pop(faults.ENV, None)
    faults.reset()
    out = eng.generate(np.arange(1, 9, dtype=np.int32),
                       SamplingParams(max_new_tokens=4, temperature=0.0))
    assert len(out) == 4


@pytest.mark.faults
def test_kv_alloc_exhaustion_with_inflight_work_retries():
    """With other work in flight, injected exhaustion parks the request
    for retry instead of shedding — pages WILL free when the in-flight
    request finishes, and the parked one then completes."""
    eng, *_ = _make(kv_page_tokens=16, kv_pool_pages=16)
    sp = SamplingParams(max_new_tokens=12, temperature=0.0)
    a = eng.submit(np.arange(1, 9, dtype=np.int32), sp)
    eng.step()                                     # a is slotted + decoding
    os.environ[faults.ENV] = "kv.alloc:1:1"        # fail exactly one alloc
    b = eng.submit(np.arange(1, 9, dtype=np.int32), sp)
    for _ in range(200):
        eng.step()
        if a.done.is_set() and b.done.is_set():
            break
    assert a.error is None and b.error is None
    assert len(a.generated) == 12 and len(b.generated) == 12


# --- engine-loop recovery ----------------------------------------------------


def test_paged_engine_loop_recovers_with_fresh_pool():
    """After an engine-loop failure the rebuilt state gets a fresh pool:
    every page, block table, and prefix entry of the poisoned pool is
    discarded, and serving continues."""
    eng, *_ = _make(kv_page_tokens=16, kv_pool_pages=16)
    sp = SamplingParams(max_new_tokens=4, temperature=0.0)
    want = eng.generate(np.arange(1, 9, dtype=np.int32), sp)

    eng.start()
    try:
        os.environ[faults.ENV] = "engine.decode:1:1"
        req = eng.submit(np.arange(1, 9, dtype=np.int32), sp)
        assert req.done.wait(20)
        assert req.error is not None
        os.environ.pop(faults.ENV, None)
        faults.reset()
        req2 = eng.submit(np.arange(1, 9, dtype=np.int32), sp)
        assert req2.done.wait(30)
        assert req2.error is None and req2.generated == want
        assert eng._pool.in_use == 0
        assert not eng._prefix_cache
    finally:
        os.environ.pop(faults.ENV, None)
        faults.reset()
        eng.stop()
