// kukecell: the namespace isolation primitive behind NamespaceBackend.
//
// TPU-native re-design of the reference's containerd/OCI layer
// (internal/ctr/spec.go:309-511 builds an OCI spec; internal/ctr/
// container.go:37-513 drives containerd): instead of delegating to an
// external runtime, one small setuid-less root helper owns the two
// namespace operations a cell needs:
//
//   kukecell sandbox --pid-file F --hostname NAME --pause BIN
//            [--host-net] [--host-pid]
//     Create the cell's shared namespace set (UTS+IPC, plus NET and PID
//     unless --host-*) with kukepause as in-namespace PID 1 (its reaper/
//     fast-SIGTERM role, reference cmd/kukepause/main.go:17-62). Writes
//     kukepause's host pid to --pid-file and exits; the sandbox lives on,
//     reparented to init, until kukepause is SIGTERMed or the last
//     process leaves.
//
//   kukecell enter --sandbox PID [--rootfs DIR] [--bind SRC:DST[:ro]]...
//            [--tmpfs DST]... [--device PATH]... [--no-dev]
//            [--readonly-root] [--cap NAME]...
//            [--privileged] [--host-net] [--host-pid] [--workdir DIR]
//            [--user UID[:GID]] -- CMD [ARGS...]
//     Join the sandbox's namespaces, build a private mount namespace
//     (pivot_root onto --rootfs when given; minimal /dev with only the
//     granted --device nodes; volume/secret binds; optional read-only
//     root), drop capabilities to the default bounded set (+ --cap adds),
//     set no_new_privs, then exec the workload. Exit code mirrors the
//     workload; TERM/INT are forwarded.
//
// The supervisor (kukeshim/kuketty) stays OUTSIDE the namespaces on host
// paths, so exit files, logs and the attach socket keep their
// daemon-restart-safe locations; only the workload itself is namespaced.
//
// Build: g++ -O2 -o kukecell kukecell.cpp

#include <cerrno>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <linux/audit.h>
#include <linux/filter.h>
#include <linux/seccomp.h>
#include <sched.h>
#include <string>
#include <sys/mount.h>
#include <sys/prctl.h>
#include <sys/stat.h>
#include <grp.h>
#include <sys/syscall.h>
#include <sys/wait.h>
#include <unistd.h>
#include <vector>

#ifndef MS_REC
#define MS_REC 16384
#endif

static void die(const char* what) {
    fprintf(stderr, "kukecell: %s: %s\n", what, strerror(errno));
    _exit(125);
}

static void write_file(const std::string& path, const std::string& content) {
    std::string tmp = path + ".tmp";
    int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd < 0) die("open pid-file");
    if (write(fd, content.c_str(), content.size()) < 0) die("write pid-file");
    close(fd);
    if (rename(tmp.c_str(), path.c_str()) != 0) die("rename pid-file");
}

// --- capabilities ----------------------------------------------------------

struct CapName { const char* name; int value; };
// Linux capability table (uapi/linux/capability.h). Names accepted with or
// without the CAP_ prefix, case-insensitive.
static const CapName kCaps[] = {
    {"CHOWN", 0}, {"DAC_OVERRIDE", 1}, {"DAC_READ_SEARCH", 2}, {"FOWNER", 3},
    {"FSETID", 4}, {"KILL", 5}, {"SETGID", 6}, {"SETUID", 7}, {"SETPCAP", 8},
    {"LINUX_IMMUTABLE", 9}, {"NET_BIND_SERVICE", 10}, {"NET_BROADCAST", 11},
    {"NET_ADMIN", 12}, {"NET_RAW", 13}, {"IPC_LOCK", 14}, {"IPC_OWNER", 15},
    {"SYS_MODULE", 16}, {"SYS_RAWIO", 17}, {"SYS_CHROOT", 18},
    {"SYS_PTRACE", 19}, {"SYS_PACCT", 20}, {"SYS_ADMIN", 21},
    {"SYS_BOOT", 22}, {"SYS_NICE", 23}, {"SYS_RESOURCE", 24},
    {"SYS_TIME", 25}, {"SYS_TTY_CONFIG", 26}, {"MKNOD", 27}, {"LEASE", 28},
    {"AUDIT_WRITE", 29}, {"AUDIT_CONTROL", 30}, {"SETFCAP", 31},
    {"MAC_OVERRIDE", 32}, {"MAC_ADMIN", 33}, {"SYSLOG", 34},
    {"WAKE_ALARM", 35}, {"BLOCK_SUSPEND", 36}, {"AUDIT_READ", 37},
    {"PERFMON", 38}, {"BPF", 39}, {"CHECKPOINT_RESTORE", 40},
};

// Default bounded set for unprivileged cells (the containerd/Docker default
// profile, which the reference inherits through containerd's oci defaults).
static const int kDefaultCaps[] = {0, 1, 3, 4, 5, 6, 7, 8, 10, 13, 18, 27, 29, 31};

static int cap_lookup(const std::string& raw) {
    std::string s = raw;
    for (auto& ch : s) ch = toupper(ch);
    if (s.rfind("CAP_", 0) == 0) s = s.substr(4);
    for (const auto& c : kCaps)
        if (s == c.name) return c.value;
    return -1;
}

// --- seccomp ---------------------------------------------------------------
//
// Default denylist filter (the Docker-default-profile subset that matters
// for a cell that already dropped its capability bounding set): kernel
// surface no agent workload needs and several namespace-escape staples.
// Reference analog: internal/ctr/spec.go security opts carry the OCI
// seccomp profile; here the filter is built directly as classic BPF so
// there is no libseccomp dependency. Denied calls fail with EPERM (not
// SIGKILL) so probing software degrades instead of dying.
static void install_seccomp_denylist() {
#ifdef __x86_64__
    static const int denied[] = {
        SYS_init_module, SYS_finit_module, SYS_delete_module,
        SYS_kexec_load, SYS_kexec_file_load, SYS_reboot,
        SYS_swapon, SYS_swapoff,
        SYS_open_by_handle_at,          // classic container escape
        SYS_perf_event_open, SYS_bpf, SYS_userfaultfd,
        SYS_mount, SYS_umount2, SYS_pivot_root, SYS_move_mount,
        SYS_fsopen, SYS_fsconfig, SYS_fsmount, SYS_open_tree,
        SYS_setns, SYS_unshare,
        SYS_keyctl, SYS_add_key, SYS_request_key,
        SYS_acct, SYS_settimeofday, SYS_clock_settime, SYS_adjtimex,
        SYS_iopl, SYS_ioperm,
        SYS_lookup_dcookie,
        SYS_process_vm_readv, SYS_process_vm_writev,
    };
    const int n = sizeof(denied) / sizeof(denied[0]);
    std::vector<sock_filter> prog;
    // arch check: kill on a foreign-arch syscall (x32 bypass).
    prog.push_back(BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                            offsetof(seccomp_data, arch)));
    prog.push_back(BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K, AUDIT_ARCH_X86_64, 1, 0));
    prog.push_back(BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_KILL_PROCESS));
    prog.push_back(BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                            offsetof(seccomp_data, nr)));
    // x32 ABI reports arch==AUDIT_ARCH_X86_64 with nr|=0x40000000 — those
    // numbers would miss every JEQ below and fall through to ALLOW, so the
    // whole x32 range is denied outright (Docker's default profile does
    // the same).
    prog.push_back(BPF_JUMP(BPF_JMP | BPF_JGE | BPF_K, 0x40000000u, 0, 1));
    prog.push_back(BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_KILL_PROCESS));
    for (int k = 0; k < n; k++) {
        // match -> jump to the shared EPERM return at the end.
        prog.push_back(BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K,
                                (unsigned)denied[k],
                                (unsigned char)(n - 1 - k + 1), 0));
    }
    prog.push_back(BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_ALLOW));
    prog.push_back(BPF_STMT(BPF_RET | BPF_K,
                            SECCOMP_RET_ERRNO | (EPERM & SECCOMP_RET_DATA)));
    sock_fprog fprog = { (unsigned short)prog.size(), prog.data() };
    // no_new_privs is already set by the caller; SECCOMP_MODE_FILTER
    // requires it for unprivileged installers.
    if (prctl(PR_SET_SECCOMP, SECCOMP_MODE_FILTER, &fprog, 0, 0) != 0)
        die("seccomp filter");
#else
    // Fail closed: a silently absent security control is worse than a
    // loud unsupported-arch error (request --seccomp unconfined to opt out).
    errno = ENOSYS;
    die("seccomp denylist not implemented for this architecture");
#endif
}

static void drop_bounding_set(const std::vector<int>& keep) {
    bool keep_all[64] = {};
    for (int c : keep)
        if (c >= 0 && c < 64) keep_all[c] = true;
    long last = prctl(PR_CAPBSET_READ, 40, 0, 0, 0) >= 0 ? 40 : 37;
    for (long cap = 0; cap <= last; cap++) {
        if (keep_all[cap]) continue;
        if (prctl(PR_CAPBSET_DROP, cap, 0, 0, 0) != 0 && errno != EINVAL)
            die("PR_CAPBSET_DROP");
    }
    // Clear ambient capabilities wholesale.
    prctl(PR_CAP_AMBIENT, PR_CAP_AMBIENT_CLEAR_ALL, 0, 0, 0);
}

// --- mounts ----------------------------------------------------------------

static void mkdir_p(const std::string& path, mode_t mode = 0755);

static void bind_mount(const std::string& src, const std::string& dst,
                       bool read_only, bool recursive) {
    struct stat st;
    if (stat(src.c_str(), &st) != 0) {
        fprintf(stderr, "kukecell: bind src %s: %s\n", src.c_str(), strerror(errno));
        _exit(125);
    }
    if (S_ISDIR(st.st_mode)) {
        mkdir_p(dst);
    } else {
        // Parent dirs + empty regular file as the bind target.
        size_t slash = dst.rfind('/');
        if (slash != std::string::npos)
            mkdir_p(dst.substr(0, slash));
        int fd = open(dst.c_str(), O_WRONLY | O_CREAT, 0644);
        if (fd >= 0) close(fd);
    }
    unsigned long flags = MS_BIND | (recursive ? MS_REC : 0);
    if (mount(src.c_str(), dst.c_str(), nullptr, flags, nullptr) != 0) {
        fprintf(stderr, "kukecell: bind %s -> %s: %s\n", src.c_str(),
                dst.c_str(), strerror(errno));
        _exit(125);
    }
    if (read_only) {
        if (mount(nullptr, dst.c_str(), nullptr,
                  MS_REMOUNT | MS_BIND | MS_RDONLY | (recursive ? MS_REC : 0),
                  nullptr) != 0)
            die("remount ro");
    }
}

struct BindSpec { std::string src, dst; bool ro; };

static void mkdir_p(const std::string& path, mode_t mode) {
    std::string acc;
    for (size_t i = 1; i <= path.size(); i++) {
        if (i == path.size() || path[i] == '/') {
            acc = path.substr(0, i);
            mkdir(acc.c_str(), mode);
        }
    }
}

// Overlayfs option values split on ':' and ','; image refs like name:tag
// appear in store paths, so escape them (kernel accepts '\' escapes).
static std::string overlay_escape(const std::string& p) {
    std::string out;
    for (char c : p) {
        if (c == ':' || c == ',' || c == '\\') out += '\\';
        out += c;
    }
    return out;
}

// Build a minimal /dev at <root>/dev: tmpfs + standard nodes bound from the
// host + ONLY the granted --device nodes. This is the airtight chip
// partitioning seam (reference: internal/ctr/devices.go:23-171 resolves and
// injects explicit device nodes; everything else is simply absent).
static void setup_dev(const std::string& root, const std::vector<std::string>& devices) {
    std::string dev = root + "/dev";
    // When masking the host's own /dev (host-rootfs cells), stash it first
    // so node sources remain reachable under the new tmpfs.
    std::string src_dev = "/dev";
    bool stashed = false;
    if (dev == "/dev") {
        src_dev = "/tmp/.kukecell-olddev";
        mkdir(src_dev.c_str(), 0700);
        if (mount("/dev", src_dev.c_str(), nullptr, MS_BIND | MS_REC, nullptr) != 0)
            die("stash /dev");
        stashed = true;
    }
    mkdir(dev.c_str(), 0755);
    if (mount("tmpfs", dev.c_str(), "tmpfs", MS_NOSUID,
              "mode=755,size=65536k") != 0)
        die("mount /dev tmpfs");
    static const char* std_nodes[] = {"null", "zero", "full", "random",
                                      "urandom", "tty"};
    for (const char* n : std_nodes) {
        std::string host = src_dev + "/" + n;
        if (access(host.c_str(), F_OK) == 0)
            bind_mount(host, dev + "/" + n, false, false);
    }
    for (const auto& d : devices) {
        if (d.rfind("/dev/", 0) != 0) continue;
        std::string host = src_dev + d.substr(4);  // src_dev + "/<node>"
        if (access(host.c_str(), F_OK) == 0)
            bind_mount(host, dev + "/" + d.substr(5), false, false);
        else
            fprintf(stderr, "kukecell: device %s not found, skipped\n", d.c_str());
    }
    if (stashed) {
        umount2(src_dev.c_str(), MNT_DETACH);
        rmdir(src_dev.c_str());
    }
    // pts with a private instance; ptmx via symlink.
    std::string pts = dev + "/pts";
    mkdir(pts.c_str(), 0755);
    if (mount("devpts", pts.c_str(), "devpts", MS_NOSUID | MS_NOEXEC,
              "newinstance,ptmxmode=0666,mode=0620") != 0)
        die("mount devpts");
    if (symlink("pts/ptmx", (dev + "/ptmx").c_str()) != 0 && errno != EEXIST)
        die("symlink ptmx");
    std::string shm = dev + "/shm";
    mkdir(shm.c_str(), 0755);
    if (mount("tmpfs", shm.c_str(), "tmpfs", MS_NOSUID | MS_NODEV,
              "mode=1777,size=65536k") != 0)
        die("mount /dev/shm");
    symlink("/proc/self/fd", (dev + "/fd").c_str());
    symlink("/proc/self/fd/0", (dev + "/stdin").c_str());
    symlink("/proc/self/fd/1", (dev + "/stdout").c_str());
    symlink("/proc/self/fd/2", (dev + "/stderr").c_str());
}

static void join_ns(pid_t pid, const char* name, int nstype) {
    char path[64];
    snprintf(path, sizeof(path), "/proc/%d/ns/%s", pid, name);
    int fd = open(path, O_RDONLY);
    if (fd < 0) {
        fprintf(stderr, "kukecell: open %s: %s\n", path, strerror(errno));
        _exit(125);
    }
    if (setns(fd, nstype) != 0) {
        fprintf(stderr, "kukecell: setns %s: %s\n", path, strerror(errno));
        _exit(125);
    }
    close(fd);
}

// --- sandbox mode ----------------------------------------------------------

static int cmd_sandbox(int argc, char** argv) {
    std::string pid_file, hostname, pause_bin;
    bool host_net = false, host_pid = false;
    for (int i = 0; i < argc; i++) {
        std::string a = argv[i];
        if (a == "--pid-file" && i + 1 < argc) pid_file = argv[++i];
        else if (a == "--hostname" && i + 1 < argc) hostname = argv[++i];
        else if (a == "--pause" && i + 1 < argc) pause_bin = argv[++i];
        else if (a == "--host-net") host_net = true;
        else if (a == "--host-pid") host_pid = true;
        else { fprintf(stderr, "kukecell sandbox: unknown arg %s\n", a.c_str()); return 2; }
    }
    if (pid_file.empty() || pause_bin.empty()) {
        fprintf(stderr, "kukecell sandbox: --pid-file and --pause required\n");
        return 2;
    }
    int flags = CLONE_NEWUTS | CLONE_NEWIPC;
    if (!host_net) flags |= CLONE_NEWNET;
    if (!host_pid) flags |= CLONE_NEWPID;
    if (unshare(flags) != 0) die("unshare");
    // CLOEXEC pipe handshake: a successful exec closes it silently; an
    // exec/setup failure writes the error message through it so the parent
    // can report it and NOT publish a dead sandbox pid.
    int pfd[2];
    if (pipe2(pfd, O_CLOEXEC) != 0) die("pipe2");
    pid_t child = fork();
    if (child < 0) die("fork");
    if (child == 0) {
        // PID 1 of the sandbox (when NEWPID): kukepause reaps + fast-exits
        // on TERM. Detach so the sandbox survives the caller.
        close(pfd[0]);
        setsid();
        if (!hostname.empty())
            if (sethostname(hostname.c_str(), hostname.size()) != 0) {
                dprintf(pfd[1], "sethostname: %s", strerror(errno));
                _exit(125);
            }
        int dn = open("/dev/null", O_RDWR);
        if (dn >= 0) { dup2(dn, 0); dup2(dn, 1); dup2(dn, 2); if (dn > 2) close(dn); }
        execl(pause_bin.c_str(), pause_bin.c_str(), (char*)nullptr);
        dprintf(pfd[1], "exec %s: %s", pause_bin.c_str(), strerror(errno));
        _exit(125);
    }
    close(pfd[1]);
    char errbuf[256];
    ssize_t n = read(pfd[0], errbuf, sizeof(errbuf) - 1);
    close(pfd[0]);
    if (n > 0) {
        errbuf[n] = '\0';
        fprintf(stderr, "kukecell: sandbox: %s\n", errbuf);
        waitpid(child, nullptr, 0);
        return 125;
    }
    write_file(pid_file, std::to_string(child));
    return 0;
}

// --- enter mode ------------------------------------------------------------

static pid_t g_workload = -1;
static void forward_sig(int sig) {
    if (g_workload > 0) kill(g_workload, sig);
}

static int cmd_enter(int argc, char** argv) {
    pid_t sandbox = -1;
    std::string rootfs, overlay_dir, workdir, user, seccomp_mode = "default";
    std::vector<BindSpec> binds;
    std::vector<std::string> tmpfs_mounts;
    std::vector<std::string> devices;
    std::vector<std::string> cap_adds;
    bool readonly_root = false, privileged = false;
    bool host_net = false, host_pid = false, no_dev = false;
    int i = 0;
    for (; i < argc; i++) {
        std::string a = argv[i];
        if (a == "--sandbox" && i + 1 < argc) sandbox = atoi(argv[++i]);
        else if (a == "--rootfs" && i + 1 < argc) rootfs = argv[++i];
        else if (a == "--overlay-dir" && i + 1 < argc) overlay_dir = argv[++i];
        else if (a == "--workdir" && i + 1 < argc) workdir = argv[++i];
        else if (a == "--user" && i + 1 < argc) user = argv[++i];
        else if (a == "--bind" && i + 1 < argc) {
            std::string spec = argv[++i];
            // SRC:DST[:ro] — strip the flag, then split at the LAST ':'
            // (image-store paths legally contain ':' from name:tag refs;
            // in-cell DSTs never do).
            bool ro = false;
            if (spec.size() > 3 && spec.substr(spec.size() - 3) == ":ro") {
                ro = true;
                spec = spec.substr(0, spec.size() - 3);
            }
            size_t sep = spec.rfind(':');
            if (sep == std::string::npos) {
                fprintf(stderr, "kukecell: bad --bind %s\n", argv[i]);
                return 2;
            }
            binds.push_back({spec.substr(0, sep), spec.substr(sep + 1), ro});
        }
        else if (a == "--tmpfs" && i + 1 < argc) tmpfs_mounts.push_back(argv[++i]);
        else if (a == "--seccomp" && i + 1 < argc) seccomp_mode = argv[++i];
        else if (a == "--device" && i + 1 < argc) devices.push_back(argv[++i]);
        else if (a == "--cap" && i + 1 < argc) cap_adds.push_back(argv[++i]);
        else if (a == "--readonly-root") readonly_root = true;
        else if (a == "--privileged") privileged = true;
        else if (a == "--host-net") host_net = true;
        else if (a == "--host-pid") host_pid = true;
        else if (a == "--no-dev") no_dev = true;
        else if (a == "--") { i++; break; }
        else { fprintf(stderr, "kukecell enter: unknown arg %s\n", a.c_str()); return 2; }
    }
    if (i >= argc) { fprintf(stderr, "kukecell enter: no command\n"); return 2; }
    if (sandbox <= 0) { fprintf(stderr, "kukecell enter: --sandbox required\n"); return 2; }

    // Resolve cap names before any namespace surgery so errors are cheap.
    std::vector<int> keep(std::begin(kDefaultCaps), std::end(kDefaultCaps));
    for (const auto& name : cap_adds) {
        int v = cap_lookup(name);
        if (v < 0) {
            fprintf(stderr, "kukecell: unknown capability %s\n", name.c_str());
            return 2;
        }
        keep.push_back(v);
    }

    // 1. Join the sandbox's shared namespaces. PID membership applies to
    //    children, hence the fork below.
    if (!host_net) join_ns(sandbox, "net", CLONE_NEWNET);
    join_ns(sandbox, "ipc", CLONE_NEWIPC);
    join_ns(sandbox, "uts", CLONE_NEWUTS);
    if (!host_pid) join_ns(sandbox, "pid", CLONE_NEWPID);

    // 2. Private mount namespace; stop propagation to the host.
    if (unshare(CLONE_NEWNS) != 0) die("unshare NEWNS");
    if (mount(nullptr, "/", nullptr, MS_REC | MS_PRIVATE, nullptr) != 0)
        die("make-rprivate /");

    bool pivot = !rootfs.empty();
    // Empty prefix for host-rootfs cells so path joins don't double the '/'.
    std::string root = pivot ? rootfs : "";
    if (pivot) {
        if (!overlay_dir.empty()) {
            // Copy-on-write view: the shared image rootfs is the (read-only)
            // lower layer; this container's writes land in its own upper
            // layer (the containerd-snapshotter analog).
            std::string upper = overlay_dir + "/upper";
            std::string work = overlay_dir + "/work";
            std::string merged = overlay_dir + "/merged";
            mkdir(overlay_dir.c_str(), 0755);
            mkdir(upper.c_str(), 0755);
            mkdir(work.c_str(), 0755);
            mkdir(merged.c_str(), 0755);
            std::string opts = "lowerdir=" + overlay_escape(rootfs) +
                               ",upperdir=" + overlay_escape(upper) +
                               ",workdir=" + overlay_escape(work);
            if (mount("overlay", merged.c_str(), "overlay", 0, opts.c_str()) != 0)
                die("mount overlay");
            root = merged;
        } else {
            // Make the rootfs a mount point of its own (shared, writable —
            // only used when the caller explicitly wants that).
            if (mount(rootfs.c_str(), rootfs.c_str(), nullptr, MS_BIND | MS_REC,
                      nullptr) != 0)
                die("bind rootfs");
        }
        mkdir((root + "/proc").c_str(), 0555);
        mkdir((root + "/tmp").c_str(), 01777);
        chmod((root + "/tmp").c_str(), 01777);
        mount("tmpfs", (root + "/tmp").c_str(), "tmpfs", MS_NOSUID, "mode=1777");
        // Fresh private /run (binds under /run/kukeon land on it).
        mkdir((root + "/run").c_str(), 0755);
        mount("tmpfs", (root + "/run").c_str(), "tmpfs", MS_NOSUID, "mode=755");
        mkdir((root + "/etc").c_str(), 0755);
        // Name resolution / identity files from the host (the runner will
        // switch these to per-cell files once cell DNS exists).
        for (const char* f : {"/etc/resolv.conf", "/etc/hosts"})
            if (access(f, F_OK) == 0)
                bind_mount(f, root + f, true, false);
    } else if (!privileged) {
        // Host-rootfs cell: private /run/kukeon so secret binds never
        // create droppings on the real host filesystem.
        mkdir("/run/kukeon", 0755);
        mount("tmpfs", "/run/kukeon", "tmpfs", MS_NOSUID, "mode=755");
    }
    // Fresh sysfs bound to the joined net namespace (a stale host /sys
    // would leak the host's interface list through /sys/class/net).
    if (pivot) {
        mkdir((root + "/sys").c_str(), 0555);
        if (mount("sysfs", (root + "/sys").c_str(), "sysfs",
                  MS_NOSUID | MS_NOEXEC | MS_NODEV | (privileged ? 0 : MS_RDONLY),
                  nullptr) != 0)
            die("mount /sys");
    } else if (!host_net) {
        mount("sysfs", "/sys", "sysfs",
              MS_NOSUID | MS_NOEXEC | MS_NODEV | (privileged ? 0 : MS_RDONLY),
              nullptr);
    }
    if (!no_dev && !privileged)
        setup_dev(root, devices);
    for (const auto& b : binds)
        bind_mount(b.src, pivot ? root + b.dst : b.dst, b.ro, true);
    // Private scratch mounts (reference: OCI spec tmpfs mounts,
    // internal/ctr/spec.go): per-cell, die with the mount namespace. In
    // the pivot case the mount point is created inside the image rootfs /
    // overlay; host-rootfs cells must target an EXISTING directory —
    // mkdir'ing it would permanently dropping-ify the real host fs (the
    // mount is private, the directory is not).
    for (const auto& t : tmpfs_mounts) {
        std::string dst = pivot ? root + t : t;
        struct stat st;
        if (pivot) {
            mkdir_p(dst);
        } else if (stat(dst.c_str(), &st) != 0 || !S_ISDIR(st.st_mode)) {
            fprintf(stderr, "kukecell: tmpfs mount point %s must be an "
                    "existing directory for host-rootfs cells\n", t.c_str());
            _exit(125);
        }
        if (mount("tmpfs", dst.c_str(), "tmpfs", MS_NOSUID | MS_NODEV,
                  "mode=1777") != 0)
            die("mount tmpfs");
    }

    if (pivot) {
        if (chdir(root.c_str()) != 0) die("chdir rootfs");
        // pivot_root(".", ".") stacks old root under new; detach it after.
        if (syscall(SYS_pivot_root, ".", ".") != 0) die("pivot_root");
        if (umount2(".", MNT_DETACH) != 0) die("umount old root");
        if (chdir("/") != 0) die("chdir /");
    }
    if (readonly_root && !privileged) {
        if (mount(nullptr, "/", nullptr, MS_REMOUNT | MS_BIND | MS_RDONLY,
                  nullptr) != 0 && pivot)
            die("remount / ro");
    }

    // 3. Fork so the workload is inside the joined PID namespace; mount a
    //    matching /proc there.
    pid_t child = fork();
    if (child < 0) die("fork");
    if (child == 0) {
        if (!host_pid || pivot) {
            // Fresh procfs for the (possibly joined) pid namespace.
            if (mount("proc", "/proc", "proc",
                      MS_NOSUID | MS_NOEXEC | MS_NODEV, nullptr) != 0 && pivot)
                die("mount /proc");
        }
        if (!privileged) {
            drop_bounding_set(keep);
            if (prctl(PR_SET_NO_NEW_PRIVS, 1, 0, 0, 0) != 0)
                die("no_new_privs");
            if (seccomp_mode != "unconfined")
                install_seccomp_denylist();
        }
        if (!user.empty()) {
            // Numeric UID[:GID] only — a name silently atoi'ing to 0 would
            // run the workload as root against the spec's intent.
            char* end = nullptr;
            uid_t uid = strtoul(user.c_str(), &end, 10);
            gid_t gid = uid;
            if (end == user.c_str() || (*end != '\0' && *end != ':')) {
                fprintf(stderr, "kukecell: --user wants numeric UID[:GID], "
                        "got %s\n", user.c_str());
                _exit(126);
            }
            if (*end == ':') {
                char* gend = nullptr;
                gid = strtoul(end + 1, &gend, 10);
                if (gend == end + 1 || *gend != '\0') {
                    fprintf(stderr, "kukecell: bad --user gid in %s\n",
                            user.c_str());
                    _exit(126);
                }
            }
            if (setgroups(0, nullptr) != 0) die("setgroups");
            if (setgid(gid) != 0) die("setgid");
            if (setuid(uid) != 0) die("setuid");
        }
        if (!workdir.empty()) {
            // Builders commonly WORKDIR a dir no instruction made; create
            // it (in the writable overlay) like the OCI runtimes do.
            mkdir_p(workdir);
            if (chdir(workdir.c_str()) != 0) {
                fprintf(stderr, "kukecell: chdir %s: %s\n", workdir.c_str(),
                        strerror(errno));
                _exit(126);
            }
        }
        execvp(argv[i], &argv[i]);
        fprintf(stderr, "kukecell: exec %s: %s\n", argv[i], strerror(errno));
        _exit(127);
    }

    g_workload = child;
    struct sigaction sa = {};
    sa.sa_handler = forward_sig;
    sigaction(SIGTERM, &sa, nullptr);
    sigaction(SIGINT, &sa, nullptr);

    int status = 0;
    while (waitpid(child, &status, 0) < 0)
        if (errno != EINTR) { status = 0; break; }
    return WIFEXITED(status) ? WEXITSTATUS(status)
         : WIFSIGNALED(status) ? 128 + WTERMSIG(status) : 1;
}

int main(int argc, char** argv) {
    if (argc < 2) {
        fprintf(stderr, "usage: kukecell sandbox|enter ...\n");
        return 2;
    }
    std::string mode = argv[1];
    if (mode == "sandbox") return cmd_sandbox(argc - 2, argv + 2);
    if (mode == "enter") return cmd_enter(argc - 2, argv + 2);
    fprintf(stderr, "kukecell: unknown mode %s\n", mode.c_str());
    return 2;
}
