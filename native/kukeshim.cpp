// kukeshim: per-container supervisor for non-attachable workloads.
//
// The process-backend analog of the containerd shim + cio.LogFile pair the
// reference relies on (internal/ctr/container.go, attachable.go:60-75): the
// daemon must be restartable without losing workloads or their exit codes,
// so a tiny native supervisor owns each workload:
//
//   kukeshim --log FILE --exit-file FILE --pid-file FILE [--cwd DIR]
//            [--cgroup DIR] -- CMD [ARGS...]
//
// - detaches into its own session (survives daemon restart),
// - writes the workload pid to --pid-file,
// - redirects workload stdout/stderr to --log,
// - optionally enters a cgroup (writes its pid to DIR/cgroup.procs before
//   spawning, so the workload inherits membership),
// - forwards SIGTERM/SIGINT to the workload (whole process group),
// - on workload exit writes the exit code to --exit-file (atomic rename).
//
// Build: g++ -O2 -o kukeshim kukeshim.cpp

#include <cerrno>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <string>
#include <sys/stat.h>
#include <sys/wait.h>
#include <unistd.h>
#include <vector>

static pid_t g_child = -1;

static void forward_signal(int sig) {
    if (g_child > 0) kill(-g_child, sig);  // whole workload process group
}

static void write_file_atomic(const std::string& path, const std::string& content) {
    std::string tmp = path + ".tmp";
    int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd < 0) return;
    ssize_t unused = write(fd, content.c_str(), content.size());
    (void)unused;
    close(fd);
    rename(tmp.c_str(), path.c_str());
}

int main(int argc, char** argv) {
    std::string log_path, exit_path, pid_path, cwd, cgroup_dir;
    int i = 1;
    for (; i < argc; i++) {
        std::string a = argv[i];
        if (a == "--log" && i + 1 < argc) log_path = argv[++i];
        else if (a == "--exit-file" && i + 1 < argc) exit_path = argv[++i];
        else if (a == "--pid-file" && i + 1 < argc) pid_path = argv[++i];
        else if (a == "--cwd" && i + 1 < argc) cwd = argv[++i];
        else if (a == "--cgroup" && i + 1 < argc) cgroup_dir = argv[++i];
        else if (a == "--") { i++; break; }
        else {
            fprintf(stderr, "kukeshim: unknown arg %s\n", a.c_str());
            return 2;
        }
    }
    if (i >= argc) {
        fprintf(stderr, "kukeshim: no command after --\n");
        return 2;
    }

    // Detach from the daemon's session so we survive its restart.
    if (setsid() < 0 && getpid() != getsid(0)) {
        // Already a session leader is fine; other errors are not fatal either.
    }
    signal(SIGHUP, SIG_IGN);

    if (!cgroup_dir.empty()) {
        std::string procs = cgroup_dir + "/cgroup.procs";
        int fd = open(procs.c_str(), O_WRONLY);
        if (fd >= 0) {
            std::string pid = std::to_string(getpid());
            ssize_t unused = write(fd, pid.c_str(), pid.size());
            (void)unused;
            close(fd);
        }
    }

    g_child = fork();
    if (g_child < 0) { perror("kukeshim: fork"); return 1; }
    if (g_child == 0) {
        // Workload: own process group; logs to file; exec.
        setpgid(0, 0);
        if (!cwd.empty() && chdir(cwd.c_str()) != 0) {
            fprintf(stderr, "kukeshim: chdir %s: %s\n", cwd.c_str(), strerror(errno));
            _exit(127);
        }
        if (!log_path.empty()) {
            int lfd = open(log_path.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0640);
            if (lfd >= 0) {
                dup2(lfd, STDOUT_FILENO);
                dup2(lfd, STDERR_FILENO);
                close(lfd);
            }
        }
        int dn = open("/dev/null", O_RDONLY);
        if (dn >= 0) { dup2(dn, STDIN_FILENO); close(dn); }
        execvp(argv[i], &argv[i]);
        fprintf(stderr, "kukeshim: exec %s: %s\n", argv[i], strerror(errno));
        _exit(127);
    }

    setpgid(g_child, g_child);
    if (!pid_path.empty()) write_file_atomic(pid_path, std::to_string(g_child));

    struct sigaction sa = {};
    sa.sa_handler = forward_signal;
    sigaction(SIGTERM, &sa, nullptr);
    sigaction(SIGINT, &sa, nullptr);

    int status = 0;
    while (waitpid(g_child, &status, 0) < 0) {
        if (errno != EINTR) { status = 0; break; }
    }
    int code = WIFEXITED(status) ? WEXITSTATUS(status)
             : WIFSIGNALED(status) ? 128 + WTERMSIG(status) : 1;
    if (!exit_path.empty()) write_file_atomic(exit_path, std::to_string(code));
    return code;
}
