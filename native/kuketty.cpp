// kuketty: in-container terminal wrapper for attachable workloads.
//
// Parity with the reference's cmd/kuketty (main.go:145,176, claimSocketListener
// :398; a Go binary wrapping the sbsh terminal library there). Role: own the
// workload's PTY so terminals survive detach/reattach and daemon restarts;
// terminal bytes flow CLI <-> kuketty directly over a unix socket, never
// through the daemon RPC (reference design point, attach/attach.go:17-23).
//
//   kuketty --socket PATH --capture FILE --exit-file FILE --pid-file FILE
//           [--cwd DIR] [--cgroup DIR] [--stage CMD]... -- CMD [ARGS...]
//
// - creates a PTY, runs `--stage` commands sequentially on it (runOn:create
//   stages), then execs the workload shell on the PTY slave,
// - listens on --socket; one attach client at a time (a new client replaces
//   the old); server->client bytes are raw PTY output,
// - client->server frames: [1B type][4B BE len][payload]; 'D' = data to the
//   PTY, 'W' = resize (payload: u16 rows, u16 cols BE),
// - appends all PTY output to --capture (terminal transcript survives
//   detach; reference: ctr/attachable.go:60-66),
// - exit code mirrors the workload's (written to --exit-file).
//
// Build: g++ -O2 -o kuketty kuketty.cpp

#include <cerrno>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <pty.h>
#include <string>
#include <sys/ioctl.h>
#include <sys/select.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/un.h>
#include <sys/wait.h>
#include <termios.h>
#include <unistd.h>
#include <vector>

static pid_t g_child = -1;
static volatile sig_atomic_t g_term = 0;
static volatile sig_atomic_t g_chld = 0;

static void on_term(int) {
    g_term = 1;
    if (g_child > 0) kill(g_child, SIGTERM);
}
static void on_chld(int) { g_chld = 1; }

static void write_file_atomic(const std::string& path, const std::string& content) {
    std::string tmp = path + ".tmp";
    int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd < 0) return;
    ssize_t unused = write(fd, content.c_str(), content.size());
    (void)unused;
    close(fd);
    rename(tmp.c_str(), path.c_str());
}

// Client input accumulates in a buffer and frames are parsed as they
// complete — a client that stalls mid-frame must never block the select
// loop (the PTY pump, capture, accepts and child-exit handling all share
// this single thread).
struct FrameBuf {
    std::vector<unsigned char> data;

    // Returns true while complete frames were consumed; sets *bad on a
    // protocol violation (oversized frame).
    bool drain(int master, bool* bad) {
        *bad = false;
        size_t off = 0;
        while (data.size() - off >= 5) {
            unsigned type = data[off];
            size_t len = ((size_t)data[off + 1] << 24) | ((size_t)data[off + 2] << 16) |
                         ((size_t)data[off + 3] << 8) | (size_t)data[off + 4];
            if (len > (1u << 20)) { *bad = true; break; }
            if (data.size() - off - 5 < len) break;   // incomplete frame
            const unsigned char* payload = data.data() + off + 5;
            if (type == 'D') {
                size_t w = 0;
                while (w < len) {
                    ssize_t n = write(master, payload + w, len - w);
                    if (n <= 0) break;
                    w += (size_t)n;
                }
            } else if (type == 'W' && len == 4) {
                struct winsize nws = {};
                nws.ws_row = (payload[0] << 8) | payload[1];
                nws.ws_col = (payload[2] << 8) | payload[3];
                ioctl(master, TIOCSWINSZ, &nws);
                if (g_child > 0) kill(g_child, SIGWINCH);
            }
            off += 5 + len;
        }
        if (off) data.erase(data.begin(), data.begin() + off);
        return true;
    }
};

int main(int argc, char** argv) {
    std::string sock_path, capture_path, exit_path, pid_path, cwd, cgroup_dir;
    std::vector<std::string> stages;
    int i = 1;
    for (; i < argc; i++) {
        std::string a = argv[i];
        if (a == "--socket" && i + 1 < argc) sock_path = argv[++i];
        else if (a == "--capture" && i + 1 < argc) capture_path = argv[++i];
        else if (a == "--exit-file" && i + 1 < argc) exit_path = argv[++i];
        else if (a == "--pid-file" && i + 1 < argc) pid_path = argv[++i];
        else if (a == "--cwd" && i + 1 < argc) cwd = argv[++i];
        else if (a == "--cgroup" && i + 1 < argc) cgroup_dir = argv[++i];
        else if (a == "--stage" && i + 1 < argc) stages.push_back(argv[++i]);
        else if (a == "--") { i++; break; }
        else { fprintf(stderr, "kuketty: unknown arg %s\n", a.c_str()); return 2; }
    }
    if (i >= argc || sock_path.empty()) {
        fprintf(stderr, "kuketty: need --socket and a command after --\n");
        return 2;
    }

    if (setsid() < 0) { /* already a leader: fine */ }
    signal(SIGHUP, SIG_IGN);

    if (!cgroup_dir.empty()) {
        std::string procs = cgroup_dir + "/cgroup.procs";
        int fd = open(procs.c_str(), O_WRONLY);
        if (fd >= 0) {
            std::string pid = std::to_string(getpid());
            ssize_t unused = write(fd, pid.c_str(), pid.size());
            (void)unused;
            close(fd);
        }
    }

    // Claim the attach socket (mode 0660; reference claims with mode/GID,
    // cmd/kuketty/main.go:398).
    unlink(sock_path.c_str());
    int ls = socket(AF_UNIX, SOCK_STREAM | SOCK_CLOEXEC, 0);
    if (ls < 0) { perror("kuketty: socket"); return 1; }
    struct sockaddr_un addr = {};
    addr.sun_family = AF_UNIX;
    if (sock_path.size() >= sizeof(addr.sun_path)) {
        fprintf(stderr, "kuketty: socket path too long (%zu)\n", sock_path.size());
        return 2;
    }
    strncpy(addr.sun_path, sock_path.c_str(), sizeof(addr.sun_path) - 1);
    if (bind(ls, (struct sockaddr*)&addr, sizeof(addr)) < 0) {
        perror("kuketty: bind");
        return 1;
    }
    chmod(sock_path.c_str(), 0660);
    if (listen(ls, 4) < 0) { perror("kuketty: listen"); return 1; }

    // PTY + workload.
    int master = -1;
    struct winsize ws = {24, 80, 0, 0};
    g_child = forkpty(&master, nullptr, nullptr, &ws);
    if (g_child < 0) { perror("kuketty: forkpty"); return 1; }
    if (g_child == 0) {
        if (!cwd.empty() && chdir(cwd.c_str()) != 0) _exit(127);
        // runOn:create stages, sequentially, visible on the PTY.
        for (const auto& s : stages) {
            int rc = system(s.c_str());
            if (rc != 0) fprintf(stderr, "kuketty: stage failed (%d): %s\n", rc, s.c_str());
        }
        execvp(argv[i], &argv[i]);
        fprintf(stderr, "kuketty: exec %s: %s\n", argv[i], strerror(errno));
        _exit(127);
    }
    if (!pid_path.empty()) write_file_atomic(pid_path, std::to_string(g_child));

    struct sigaction sa = {};
    sa.sa_handler = on_term;
    sigaction(SIGTERM, &sa, nullptr);
    sigaction(SIGINT, &sa, nullptr);
    sa.sa_handler = on_chld;
    sa.sa_flags = SA_NOCLDSTOP;
    sigaction(SIGCHLD, &sa, nullptr);

    int capture_fd = -1;
    if (!capture_path.empty())
        capture_fd = open(capture_path.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0640);

    int client = -1;
    FrameBuf client_buf;
    bool child_exited = false;
    int status = 0;
    unsigned char buf[4096];

    while (!child_exited || client >= 0) {
        if (g_chld) {
            g_chld = 0;
            pid_t r = waitpid(g_child, &status, WNOHANG);
            if (r == g_child) child_exited = true;
        }
        if (child_exited) break;

        fd_set rfds;
        FD_ZERO(&rfds);
        FD_SET(master, &rfds);
        FD_SET(ls, &rfds);
        if (client >= 0) FD_SET(client, &rfds);
        int maxfd = master > ls ? master : ls;
        if (client > maxfd) maxfd = client;
        struct timeval tv = {1, 0};
        int n = select(maxfd + 1, &rfds, nullptr, nullptr, &tv);
        if (n < 0) {
            if (errno == EINTR) continue;
            break;
        }

        if (FD_ISSET(master, &rfds)) {
            ssize_t r = read(master, buf, sizeof(buf));
            if (r > 0) {
                if (capture_fd >= 0) { ssize_t u = write(capture_fd, buf, r); (void)u; }
                if (client >= 0) {
                    ssize_t w = write(client, buf, r);
                    if (w < 0) { close(client); client = -1; }
                }
            } else if (r <= 0 && errno != EAGAIN && errno != EINTR) {
                // PTY closed: workload gone (or exiting).
            }
        }
        if (FD_ISSET(ls, &rfds)) {
            int c = accept(ls, nullptr, nullptr);
            if (c >= 0) {
                if (client >= 0) close(client);   // new attach replaces old
                fcntl(c, F_SETFL, fcntl(c, F_GETFL, 0) | O_NONBLOCK);
                client = c;
                client_buf.data.clear();
            }
        }
        if (client >= 0 && FD_ISSET(client, &rfds)) {
            unsigned char in[4096];
            ssize_t r = read(client, in, sizeof(in));
            if (r == 0 || (r < 0 && errno != EAGAIN && errno != EINTR)) {
                close(client);
                client = -1;
                client_buf.data.clear();
            } else if (r > 0) {
                if (client_buf.data.size() + (size_t)r > (2u << 20)) {
                    close(client);   // runaway unframed garbage
                    client = -1;
                    client_buf.data.clear();
                } else {
                    client_buf.data.insert(client_buf.data.end(), in, in + r);
                    bool bad = false;
                    client_buf.drain(master, &bad);
                    if (bad) {
                        close(client);
                        client = -1;
                        client_buf.data.clear();
                    }
                }
            }
        }
    }

    // Drain any final PTY output into the capture/client.
    for (;;) {
        ssize_t r = read(master, buf, sizeof(buf));
        if (r <= 0) break;
        if (capture_fd >= 0) { ssize_t u = write(capture_fd, buf, r); (void)u; }
        if (client >= 0) { ssize_t u = write(client, buf, r); (void)u; }
    }
    if (!child_exited) {
        waitpid(g_child, &status, 0);
    }
    if (client >= 0) close(client);
    if (capture_fd >= 0) close(capture_fd);
    close(ls);
    unlink(sock_path.c_str());

    int code = WIFEXITED(status) ? WEXITSTATUS(status)
             : WIFSIGNALED(status) ? 128 + WTERMSIG(status) : 1;
    if (!exit_path.empty()) write_file_atomic(exit_path, std::to_string(code));
    return code;
}
