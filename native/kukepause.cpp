// kukepause: minimal pause process serving as every cell's root task.
//
// Parity with the reference's cmd/kukepause/main.go:17-62 (a static
// CGO_ENABLED=0 Go binary there; C++ here): SIGTERM/SIGINT exit 0
// immediately so cell teardown doesn't eat the 10s SIGKILL escalation that
// `sleep infinity` (which ignores SIGTERM) forced, and SIGCHLD children are
// reaped so the cell never accumulates zombies.
//
// Build: g++ -O2 -static -o kukepause kukepause.cpp

#include <csignal>
#include <cstdlib>
#include <sys/wait.h>
#include <unistd.h>

int main() {
    sigset_t set;
    sigemptyset(&set);
    sigaddset(&set, SIGTERM);
    sigaddset(&set, SIGINT);
    sigaddset(&set, SIGCHLD);
    sigprocmask(SIG_BLOCK, &set, nullptr);

    for (;;) {
        int sig = 0;
        if (sigwait(&set, &sig) != 0) continue;
        if (sig == SIGTERM || sig == SIGINT) return 0;
        if (sig == SIGCHLD) {
            // Reap everything currently reapable.
            while (waitpid(-1, nullptr, WNOHANG) > 0) {}
        }
    }
}
