// kukenet: minimal iptables userspace for hosts without the iptables CLI.
//
// The kernel side of iptables (CONFIG_IP_NF_IPTABLES=y, xt_conntrack,
// xt_state, xt_comment, xt_tcpudp) is compiled into many minimal hosts
// that ship no userspace tools. kukenet speaks the xtables ABI directly —
// IPT_SO_GET_INFO / IPT_SO_GET_ENTRIES / IPT_SO_SET_REPLACE on a raw
// socket — so the egress-policy subsystem (the reference's
// internal/netpolicy, enforcer.go:34-232) enforces for real instead of
// degrading to no-op.
//
// Owns the WHOLE filter table: the caller (NetworkManager) composes the
// complete desired rule set every reconcile tick and kukenet replaces the
// table atomically in one kernel commit — the same fail-closed property
// the reference gets from iptables-restore --noflush (a default-deny
// chain never exists without its terminal DROP).
//
//   kukenet apply   — read the table spec from stdin (line protocol
//                     below), build the ipt_replace blob, commit it.
//   kukenet dump    — print the live filter table (chains + rules).
//   kukenet check   — exit 0 if the kernel xtables ABI is usable.
//
// Line protocol (one directive per line, '#' comments):
//   policy <INPUT|FORWARD|OUTPUT> <ACCEPT|DROP>
//   chain <name>
//   rule chain=<name> [src=CIDR] [dst=CIDR] [proto=tcp|udp] [dport=N]
//        [in=IFACE[+]] [out=IFACE[+]] [state=EST_REL] [comment=...]
//        verdict=<ACCEPT|DROP|RETURN|chain-name>
// Rules append in input order; 'comment' must be the LAST key (it may
// contain spaces).
//
// Build: g++ -O2 -o kukenet kukenet.cpp

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <linux/netfilter/x_tables.h>
#include <linux/netfilter/xt_comment.h>
#include <linux/netfilter/xt_state.h>
#include <linux/netfilter/xt_tcpudp.h>
#include <linux/netfilter_ipv4/ip_tables.h>
#include <map>
#include <netinet/in.h>
#include <string>
#include <sys/socket.h>
#include <unistd.h>
#include <vector>

#define ALIGN8(x) (((x) + 7u) & ~7u)

static const unsigned FILTER_HOOKS[] = {NF_INET_LOCAL_IN, NF_INET_FORWARD,
                                        NF_INET_LOCAL_OUT};
static const char* HOOK_NAMES[] = {"INPUT", "FORWARD", "OUTPUT"};

// --- parsed model -----------------------------------------------------------

struct RuleSpec {
    std::string chain;
    std::string src, dst;        // CIDR
    std::string proto;           // "", "tcp", "udp"
    int dport = -1;
    std::string in_iface, out_iface;
    bool state_est_rel = false;
    std::string comment;
    std::string verdict;         // ACCEPT | DROP | RETURN | <chain>
};

struct TableSpec {
    std::map<std::string, std::string> policies = {
        {"INPUT", "ACCEPT"}, {"FORWARD", "ACCEPT"}, {"OUTPUT", "ACCEPT"}};
    std::vector<std::string> user_chains;   // declaration order
    std::vector<RuleSpec> rules;            // global order
};

static bool parse_cidr(const std::string& cidr, in_addr* addr, in_addr* mask) {
    std::string ip = cidr;
    int prefix = 32;
    size_t slash = cidr.find('/');
    if (slash != std::string::npos) {
        ip = cidr.substr(0, slash);
        prefix = atoi(cidr.c_str() + slash + 1);
    }
    if (inet_pton(AF_INET, ip.c_str(), addr) != 1) return false;
    uint32_t m = prefix == 0 ? 0 : htonl(~uint32_t(0) << (32 - prefix));
    mask->s_addr = m;
    addr->s_addr &= m;   // kernel requires the address pre-masked
    return true;
}

static bool parse_spec(FILE* in, TableSpec* t, std::string* err) {
    char buf[1024];
    int lineno = 0;
    while (fgets(buf, sizeof(buf), in)) {
        lineno++;
        std::string line = buf;
        while (!line.empty() && (line.back() == '\n' || line.back() == '\r'))
            line.pop_back();
        if (line.empty() || line[0] == '#') continue;
        size_t sp = line.find(' ');
        std::string kw = line.substr(0, sp);
        std::string rest = sp == std::string::npos ? "" : line.substr(sp + 1);
        if (kw == "policy") {
            size_t s2 = rest.find(' ');
            std::string hook = rest.substr(0, s2);
            std::string pol = s2 == std::string::npos ? "" : rest.substr(s2 + 1);
            if (!t->policies.count(hook) || (pol != "ACCEPT" && pol != "DROP")) {
                *err = "line " + std::to_string(lineno) + ": bad policy";
                return false;
            }
            t->policies[hook] = pol;
        } else if (kw == "chain") {
            if (rest.empty() || rest.size() >= XT_EXTENSION_MAXNAMELEN) {
                *err = "line " + std::to_string(lineno) + ": bad chain name";
                return false;
            }
            t->user_chains.push_back(rest);
        } else if (kw == "rule") {
            RuleSpec r;
            std::string remaining = rest;
            while (!remaining.empty()) {
                size_t eq = remaining.find('=');
                if (eq == std::string::npos) break;
                std::string key = remaining.substr(0, eq);
                std::string val;
                if (key == "comment") {       // consumes the rest of the line
                    val = remaining.substr(eq + 1);
                    remaining.clear();
                } else {
                    size_t end = remaining.find(' ', eq + 1);
                    val = remaining.substr(eq + 1,
                        end == std::string::npos ? std::string::npos : end - eq - 1);
                    remaining = end == std::string::npos ? "" : remaining.substr(end + 1);
                }
                if (key == "chain") r.chain = val;
                else if (key == "src") r.src = val;
                else if (key == "dst") r.dst = val;
                else if (key == "proto") r.proto = val;
                else if (key == "dport") r.dport = atoi(val.c_str());
                else if (key == "in") r.in_iface = val;
                else if (key == "out") r.out_iface = val;
                else if (key == "state") r.state_est_rel = (val == "EST_REL");
                else if (key == "comment") r.comment = val;
                else if (key == "verdict") r.verdict = val;
                else {
                    *err = "line " + std::to_string(lineno) + ": unknown key " + key;
                    return false;
                }
            }
            if (r.chain.empty() || r.verdict.empty()) {
                *err = "line " + std::to_string(lineno) + ": rule needs chain= and verdict=";
                return false;
            }
            t->rules.push_back(r);
        } else {
            *err = "line " + std::to_string(lineno) + ": unknown directive " + kw;
            return false;
        }
    }
    return true;
}

// --- blob building ----------------------------------------------------------

struct Blob {
    std::vector<uint8_t> data;
    size_t append(const void* p, size_t n) {
        size_t off = data.size();
        data.insert(data.end(), (const uint8_t*)p, (const uint8_t*)p + n);
        return off;
    }
    size_t pad_to(size_t aligned_size, size_t start) {
        while (data.size() - start < aligned_size) data.push_back(0);
        return data.size();
    }
};

// Serialized sizes (all 8-aligned).
static const size_t SZ_STD_TARGET =
    ALIGN8(sizeof(xt_entry_target) + sizeof(int));
static const size_t SZ_ERR_TARGET =
    ALIGN8(sizeof(xt_entry_target) + XT_FUNCTION_MAXNAMELEN);

static void set_iface(char* iface, unsigned char* mask, const std::string& spec) {
    // "eth0" exact: mask covers name + NUL. "k-+" prefix: mask covers the
    // prefix chars only.
    bool prefix = !spec.empty() && spec.back() == '+';
    std::string name = prefix ? spec.substr(0, spec.size() - 1) : spec;
    snprintf(iface, IFNAMSIZ, "%s", name.c_str());
    size_t n = prefix ? name.size() : name.size() + 1;
    if (n > IFNAMSIZ) n = IFNAMSIZ;
    memset(mask, 0xFF, n);
}

// Append one ipt_entry (with matches + target). Returns entry offset.
static size_t emit_rule(Blob* b, const RuleSpec& r,
                        const std::map<std::string, int>& builtin_verdicts,
                        std::map<size_t, std::string>* pending_jumps,
                        std::string* err) {
    size_t start = b->data.size();
    ipt_entry e = {};
    if (!r.src.empty() && !parse_cidr(r.src, &e.ip.src, &e.ip.smsk)) {
        *err = "bad src " + r.src;
        return SIZE_MAX;
    }
    if (!r.dst.empty() && !parse_cidr(r.dst, &e.ip.dst, &e.ip.dmsk)) {
        *err = "bad dst " + r.dst;
        return SIZE_MAX;
    }
    if (!r.in_iface.empty()) {
        std::string spec = r.in_iface;
        if (spec[0] == '!') {           // "in=!IFACE" inverted match
            e.ip.invflags |= IPT_INV_VIA_IN;
            spec = spec.substr(1);
        }
        set_iface(e.ip.iniface, e.ip.iniface_mask, spec);
    }
    if (!r.out_iface.empty()) {
        std::string spec = r.out_iface;
        if (spec[0] == '!') {
            e.ip.invflags |= IPT_INV_VIA_OUT;
            spec = spec.substr(1);
        }
        set_iface(e.ip.outiface, e.ip.outiface_mask, spec);
    }
    if (r.proto == "tcp") e.ip.proto = IPPROTO_TCP;
    else if (r.proto == "udp") e.ip.proto = IPPROTO_UDP;
    b->append(&e, sizeof(e));

    // Matches.
    if (r.state_est_rel) {
        size_t msz = ALIGN8(sizeof(xt_entry_match) + sizeof(xt_state_info));
        std::vector<uint8_t> m(msz, 0);
        auto* em = (xt_entry_match*)m.data();
        em->u.user.match_size = msz;
        snprintf(em->u.user.name, sizeof(em->u.user.name), "state");
        auto* si = (xt_state_info*)(m.data() + sizeof(xt_entry_match));
        // XT_STATE_BIT(IP_CT_ESTABLISHED)=2 | XT_STATE_BIT(IP_CT_RELATED)=4
        si->statemask = 6;
        b->append(m.data(), msz);
    }
    if (r.dport >= 0) {
        size_t msz = ALIGN8(sizeof(xt_entry_match) + sizeof(xt_tcp));
        std::vector<uint8_t> m(msz, 0);
        auto* em = (xt_entry_match*)m.data();
        em->u.user.match_size = msz;
        bool udp = r.proto == "udp";
        snprintf(em->u.user.name, sizeof(em->u.user.name), udp ? "udp" : "tcp");
        if (udp) {
            auto* x = (xt_udp*)(m.data() + sizeof(xt_entry_match));
            x->spts[0] = 0; x->spts[1] = 0xFFFF;
            x->dpts[0] = x->dpts[1] = (uint16_t)r.dport;
        } else {
            auto* x = (xt_tcp*)(m.data() + sizeof(xt_entry_match));
            x->spts[0] = 0; x->spts[1] = 0xFFFF;
            x->dpts[0] = x->dpts[1] = (uint16_t)r.dport;
        }
        b->append(m.data(), msz);
    }
    if (!r.comment.empty()) {
        size_t msz = ALIGN8(sizeof(xt_entry_match) + sizeof(xt_comment_info));
        std::vector<uint8_t> m(msz, 0);
        auto* em = (xt_entry_match*)m.data();
        em->u.user.match_size = msz;
        snprintf(em->u.user.name, sizeof(em->u.user.name), "comment");
        auto* ci = (xt_comment_info*)(m.data() + sizeof(xt_entry_match));
        snprintf((char*)ci->comment, sizeof(ci->comment), "%s", r.comment.c_str());
        b->append(m.data(), msz);
    }

    size_t target_off = b->data.size() - start;
    // Target.
    std::vector<uint8_t> tg(SZ_STD_TARGET, 0);
    auto* et = (xt_entry_target*)tg.data();
    et->u.user.target_size = SZ_STD_TARGET;
    // Standard target: empty name.
    auto it = builtin_verdicts.find(r.verdict);
    int* verdict = (int*)(tg.data() + sizeof(xt_entry_target));
    if (it != builtin_verdicts.end()) {
        *verdict = it->second;
    } else {
        // Jump to user chain: patched once chain offsets are known.
        (*pending_jumps)[b->data.size() + sizeof(xt_entry_target)] = r.verdict;
        *verdict = 0;
    }
    b->append(tg.data(), tg.size());

    auto* entry = (ipt_entry*)(b->data.data() + start);
    entry->target_offset = target_off;
    entry->next_offset = b->data.size() - start;
    return start;
}

static size_t emit_unconditional(Blob* b, int verdict) {
    size_t start = b->data.size();
    ipt_entry e = {};
    e.target_offset = sizeof(ipt_entry);
    e.next_offset = sizeof(ipt_entry) + SZ_STD_TARGET;
    b->append(&e, sizeof(e));
    std::vector<uint8_t> tg(SZ_STD_TARGET, 0);
    auto* et = (xt_entry_target*)tg.data();
    et->u.user.target_size = SZ_STD_TARGET;
    *(int*)(tg.data() + sizeof(xt_entry_target)) = verdict;
    b->append(tg.data(), tg.size());
    return start;
}

static size_t emit_error_node(Blob* b, const std::string& name) {
    size_t start = b->data.size();
    ipt_entry e = {};
    e.target_offset = sizeof(ipt_entry);
    e.next_offset = sizeof(ipt_entry) + SZ_ERR_TARGET;
    b->append(&e, sizeof(e));
    std::vector<uint8_t> tg(SZ_ERR_TARGET, 0);
    auto* et = (xt_entry_target*)tg.data();
    et->u.user.target_size = SZ_ERR_TARGET;
    snprintf(et->u.user.name, sizeof(et->u.user.name), "ERROR");
    snprintf((char*)tg.data() + sizeof(xt_entry_target),
             XT_FUNCTION_MAXNAMELEN, "%s", name.c_str());
    b->append(tg.data(), tg.size());
    return start;
}

static const int V_ACCEPT = -NF_ACCEPT - 1;   // -2
static const int V_DROP = -NF_DROP - 1;       // -1
static const int V_RETURN = XT_RETURN;        // -NF_REPEAT-1 = -5

static int cmd_apply() {
    TableSpec spec;
    std::string err;
    if (!parse_spec(stdin, &spec, &err)) {
        fprintf(stderr, "kukenet: %s\n", err.c_str());
        return 2;
    }
    std::map<std::string, int> builtin = {
        {"ACCEPT", V_ACCEPT}, {"DROP", V_DROP}, {"RETURN", V_RETURN}};

    Blob b;
    unsigned hook_entry[NF_INET_NUMHOOKS] = {};
    unsigned underflow[NF_INET_NUMHOOKS] = {};
    unsigned num_entries = 0;
    std::map<size_t, std::string> pending;  // offset of verdict int -> chain
    std::map<std::string, size_t> chain_start;

    for (int h = 0; h < 3; h++) {
        const char* hn = HOOK_NAMES[h];
        hook_entry[FILTER_HOOKS[h]] = b.data.size();
        for (const auto& r : spec.rules) {
            if (r.chain != hn) continue;
            if (emit_rule(&b, r, builtin, &pending, &err) == SIZE_MAX) {
                fprintf(stderr, "kukenet: %s\n", err.c_str());
                return 2;
            }
            num_entries++;
        }
        underflow[FILTER_HOOKS[h]] = b.data.size();
        emit_unconditional(&b, spec.policies[hn] == "DROP" ? V_DROP : V_ACCEPT);
        num_entries++;
    }
    for (const auto& cn : spec.user_chains) {
        emit_error_node(&b, cn);
        num_entries++;
        chain_start[cn] = b.data.size();   // first rule of the chain
        for (const auto& r : spec.rules) {
            if (r.chain != cn) continue;
            if (emit_rule(&b, r, builtin, &pending, &err) == SIZE_MAX) {
                fprintf(stderr, "kukenet: %s\n", err.c_str());
                return 2;
            }
            num_entries++;
        }
        emit_unconditional(&b, V_RETURN);   // implicit chain policy
        num_entries++;
    }
    emit_error_node(&b, "ERROR");
    num_entries++;

    // Patch user-chain jumps (verdict = offset of the chain's ERROR node;
    // the kernel skips the node and enters the first rule).
    for (const auto& [off, chain] : pending) {
        auto it = chain_start.find(chain);
        if (it == chain_start.end()) {
            fprintf(stderr, "kukenet: jump to undeclared chain %s\n", chain.c_str());
            return 2;
        }
        *(int*)(b.data.data() + off) = (int)it->second;
    }

    int fd = socket(AF_INET, SOCK_RAW, IPPROTO_RAW);
    if (fd < 0) { perror("kukenet: socket"); return 1; }

    // Old counter count for the replace call.
    ipt_getinfo info = {};
    snprintf(info.name, sizeof(info.name), "filter");
    socklen_t ilen = sizeof(info);
    if (getsockopt(fd, IPPROTO_IP, IPT_SO_GET_INFO, &info, &ilen) != 0) {
        perror("kukenet: IPT_SO_GET_INFO");
        close(fd);
        return 1;
    }

    std::vector<uint8_t> rep(sizeof(ipt_replace) + b.data.size());
    auto* r = (ipt_replace*)rep.data();
    snprintf(r->name, sizeof(r->name), "filter");
    r->valid_hooks = info.valid_hooks;
    r->num_entries = num_entries;
    r->size = b.data.size();
    memcpy(r->hook_entry, hook_entry, sizeof(hook_entry));
    memcpy(r->underflow, underflow, sizeof(underflow));
    // Unused hooks must still carry valid offsets? For filter the kernel
    // checks only hooks in valid_hooks; leave the rest zero.
    std::vector<xt_counters> old_counters(info.num_entries);
    r->num_counters = info.num_entries;
    r->counters = old_counters.data();
    memcpy(r->entries, b.data.data(), b.data.size());

    if (setsockopt(fd, IPPROTO_IP, IPT_SO_SET_REPLACE, rep.data(),
                   rep.size()) != 0) {
        perror("kukenet: IPT_SO_SET_REPLACE");
        close(fd);
        return 1;
    }
    close(fd);
    return 0;
}

static int cmd_dump() {
    int fd = socket(AF_INET, SOCK_RAW, IPPROTO_RAW);
    if (fd < 0) { perror("kukenet: socket"); return 1; }
    ipt_getinfo info = {};
    snprintf(info.name, sizeof(info.name), "filter");
    socklen_t ilen = sizeof(info);
    if (getsockopt(fd, IPPROTO_IP, IPT_SO_GET_INFO, &info, &ilen) != 0) {
        perror("kukenet: IPT_SO_GET_INFO");
        return 1;
    }
    std::vector<uint8_t> buf(sizeof(ipt_get_entries) + info.size);
    auto* ge = (ipt_get_entries*)buf.data();
    snprintf(ge->name, sizeof(ge->name), "filter");
    ge->size = info.size;
    socklen_t glen = buf.size();
    if (getsockopt(fd, IPPROTO_IP, IPT_SO_GET_ENTRIES, buf.data(), &glen) != 0) {
        perror("kukenet: IPT_SO_GET_ENTRIES");
        return 1;
    }
    close(fd);

    printf("# filter table: %u entries, %u bytes, hooks 0x%x\n",
           info.num_entries, info.size, info.valid_hooks);
    size_t off = 0;
    std::string cur = "";
    for (int h = 0; h < 3; h++)
        printf("# hook %s at %u, underflow %u\n", HOOK_NAMES[h],
               info.hook_entry[FILTER_HOOKS[h]], info.underflow[FILTER_HOOKS[h]]);
    while (off < info.size) {
        auto* e = (ipt_entry*)((uint8_t*)ge->entrytable + off);
        auto* tgt = (xt_entry_target*)((uint8_t*)e + e->target_offset);
        for (int h = 0; h < 3; h++)
            if (off == info.hook_entry[FILTER_HOOKS[h]]) cur = HOOK_NAMES[h];
        if (strcmp(tgt->u.user.name, "ERROR") == 0) {
            const char* nm = (const char*)tgt + sizeof(xt_entry_target);
            if (strcmp(nm, "ERROR") != 0) {
                cur = nm;
                printf("chain %s\n", nm);
            }
        } else {
            char src[32] = "any", dst[32] = "any";
            if (e->ip.smsk.s_addr) {
                inet_ntop(AF_INET, &e->ip.src, src, sizeof(src));
            }
            if (e->ip.dmsk.s_addr) {
                inet_ntop(AF_INET, &e->ip.dst, dst, sizeof(dst));
            }
            printf("rule chain=%s src=%s dst=%s proto=%u in=%s ",
                   cur.c_str(), src, dst, e->ip.proto,
                   e->ip.iniface[0] ? e->ip.iniface : "any");
            // Matches.
            size_t moff = sizeof(ipt_entry);
            while (moff < e->target_offset) {
                auto* m = (xt_entry_match*)((uint8_t*)e + moff);
                printf("match=%s ", m->u.user.name);
                moff += m->u.user.match_size;
            }
            if (tgt->u.user.name[0] == '\0') {
                int v = *(int*)((uint8_t*)tgt + sizeof(xt_entry_target));
                if (v == V_ACCEPT) printf("verdict=ACCEPT");
                else if (v == V_DROP) printf("verdict=DROP");
                else if (v == V_RETURN) printf("verdict=RETURN");
                else printf("verdict=jump:%d", v);
            } else {
                printf("verdict=%s", tgt->u.user.name);
            }
            printf(" pkts=%llu bytes=%llu\n",
                   (unsigned long long)e->counters.pcnt,
                   (unsigned long long)e->counters.bcnt);
        }
        off += e->next_offset;
        if (e->next_offset == 0) break;
    }
    return 0;
}

static int cmd_check() {
    int fd = socket(AF_INET, SOCK_RAW, IPPROTO_RAW);
    if (fd < 0) return 1;
    ipt_getinfo info = {};
    snprintf(info.name, sizeof(info.name), "filter");
    socklen_t ilen = sizeof(info);
    int rc = getsockopt(fd, IPPROTO_IP, IPT_SO_GET_INFO, &info, &ilen);
    close(fd);
    return rc == 0 ? 0 : 1;
}

int main(int argc, char** argv) {
    if (argc < 2) {
        fprintf(stderr, "usage: kukenet apply|dump|check\n");
        return 2;
    }
    std::string mode = argv[1];
    if (mode == "apply") return cmd_apply();
    if (mode == "dump") return cmd_dump();
    if (mode == "check") return cmd_check();
    fprintf(stderr, "kukenet: unknown mode %s\n", mode.c_str());
    return 2;
}
