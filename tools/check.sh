#!/usr/bin/env bash
# Pre-PR gate: byte-compile the tree, run kukelint (strict baseline mode —
# stale suppressions fail too), verify the guarded-by contract is not
# stale, and run mypy on the strictly-annotated modules when mypy is
# installed. Exits non-zero on any new finding.
#
#   ./tools/check.sh               # static gates (seconds, no jax import)
#   ./tools/check.sh --sanitize    # + the kukesan fixture/stress tests
#                                  #   under KUKEON_SANITIZE=1 (needs jax)
#
# The full dynamic gate is the whole tier-1 suite under KUKEON_SANITIZE=1
# (see README "Concurrency model"); --sanitize is the fast slice of it.
#
# This is the same set of checks tier-1 runs via
# tests/test_static_analysis.py, packaged for the editing loop.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "check.sh: compileall"
python -m compileall -q kukeon_tpu tests bench.py

echo "check.sh: kukelint (python -m kukeon_tpu.analysis)"
python -m kukeon_tpu.analysis --strict-baseline

echo "check.sh: guarded-by contract drift"
python - <<'EOF'
from kukeon_tpu.analysis import (
    default_contracts_path, guarded_contracts, load_sources,
    render_contracts,
)
import os, sys
import kukeon_tpu

root = os.path.dirname(os.path.abspath(kukeon_tpu.__file__))
want = render_contracts(guarded_contracts(load_sources(root), root))
with open(default_contracts_path(), encoding="utf-8") as f:
    have = f.read()
if have != want:
    sys.exit("analysis/guarded_by.json is stale — regenerate with "
             "`python -m kukeon_tpu.analysis --write-contracts`")
print("guarded_by.json matches the tree")
EOF

echo "check.sh: bench trajectory diff (informational)"
python tools/bench_compare.py || \
    echo "check.sh: bench_compare reports a regression (informational —" \
         "inspect the newest BENCH_r*.json; a CPU-degraded round on a" \
         "wedged TPU host is a fact, not a gate)"

if python -c "import mypy" >/dev/null 2>&1; then
    echo "check.sh: mypy (strict modules)"
    python -m mypy kukeon_tpu/obs/registry.py kukeon_tpu/serving/kv_pages.py \
        kukeon_tpu/gateway/router.py kukeon_tpu/sanitize
else
    echo "check.sh: mypy not installed — skipping the strict-module check"
fi

if [[ "${1:-}" == "--sanitize" ]]; then
    echo "check.sh: kukesan fixture/stress tests (KUKEON_SANITIZE=1)"
    JAX_PLATFORMS=cpu KUKEON_SANITIZE=1 python -m pytest \
        tests/test_concurrency_sanitizer.py -q -p no:cacheprovider
fi

echo "check.sh: all gates green"
