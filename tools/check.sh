#!/usr/bin/env bash
# Pre-PR gate: byte-compile the tree, run kukelint (strict baseline mode —
# stale suppressions fail too), and run mypy on the strictly-annotated
# modules when mypy is installed. Exits non-zero on any new finding.
#
#   ./tools/check.sh
#
# This is the same set of checks tier-1 runs via
# tests/test_static_analysis.py, packaged for the editing loop: seconds,
# no jax import, no test collection.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "check.sh: compileall"
python -m compileall -q kukeon_tpu tests bench.py

echo "check.sh: kukelint (python -m kukeon_tpu.analysis)"
python -m kukeon_tpu.analysis --strict-baseline

if python -c "import mypy" >/dev/null 2>&1; then
    echo "check.sh: mypy (strict modules)"
    python -m mypy kukeon_tpu/obs/registry.py kukeon_tpu/serving/kv_pages.py
else
    echo "check.sh: mypy not installed — skipping the strict-module check"
fi

echo "check.sh: all gates green"
