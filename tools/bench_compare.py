#!/usr/bin/env python3
"""Diff the newest BENCH_rNN.json trajectory artifact against the
previous round and flag regressions.

The bench artifacts (`bench.py --out BENCH_rNN.json`, schema
kukeon-bench/v1..v8) are the repo's performance trajectory; this tool is
the cheap guard that a round did not silently give back throughput,
latency, cold start, or HBM headroom:

    python tools/bench_compare.py                 # newest vs previous
    python tools/bench_compare.py --threshold 5   # stricter gate (%)
    python tools/bench_compare.py A.json B.json   # explicit pair (old new)

Exit codes: 0 = no regression past the threshold (or fewer than two
comparable artifacts — early rounds logged raw run transcripts, not
artifacts, and those are skipped, not errors), 1 = regression, 2 = usage.
Wired into tools/check.sh as an informational step: a CPU-degraded round
on a wedged TPU host (see ROADMAP "Perf/verify trajectory") is a fact to
surface, not a reason to block unrelated work.

Zero dependencies on bench.py (which imports jax): the schema-upgrade
shim here mirrors bench.read_artifact and is pinned against it by
tests/test_tsdb.py.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

SCHEMAS = ("kukeon-bench/v1", "kukeon-bench/v2", "kukeon-bench/v3",
           "kukeon-bench/v4", "kukeon-bench/v5", "kukeon-bench/v6",
           "kukeon-bench/v7", "kukeon-bench/v8")

# (label, path into the artifact, direction: +1 = higher is better)
METRICS = (
    ("tok/s", ("tok_per_s",), +1),
    # v8: the roofline headline — the busiest program's model-FLOPs
    # utilization from the engine's own ProgramTimers. A drop at equal
    # tok/s means the same throughput now burns more device time.
    ("MFU", ("mfu",), +1),
    ("ttft p95 (s)", ("latency_s", "ttft", "p95"), -1),
    # v4: the top-level client-observable TTFT p95 (disagg runs measure it
    # through the gateway; classic runs lift it from latency_s) and the KV
    # handoff cost — a regression here means the prefill->decode transfer
    # path got slower, the disaggregation's whole budget.
    ("ttft p95 (s, v4)", ("ttft_p95_s",), -1),
    ("handoff p50 (ms)", ("handoff_ms_p50",), -1),
    ("e2e p95 (s)", ("latency_s", "e2e", "p95"), -1),
    ("cold start p50 (s)", ("cold_start", "p50_s"), -1),
    # v6: the streamed-boot load sub-phases (work-time medians off the
    # cell's own gauges). These overlap each other and compile, so a
    # regression in any one of them names WHICH leg of the boot pipeline
    # got slower even when the overlapped total hides it.
    ("cold disk (s)", ("cold_start", "load_s", "disk"), -1),
    ("cold cast (s)", ("cold_start", "load_s", "cast"), -1),
    ("cold upload (s)", ("cold_start", "load_s", "upload"), -1),
    ("peak HBM (bytes)", ("peak_hbm_bytes",), -1),
    # v5: the diurnal ramp's headline numbers — the peak stage's client
    # p95 (the latency the spillover queue trades a shed storm for) and
    # failed requests over the whole ramp (contract: zero).
    ("diurnal peak p95 (s)", ("diurnal", "peak_p95_s"), -1),
    ("diurnal failed", ("diurnal", "failed"), -1),
)


def read_artifact(path: str) -> dict | None:
    """A BENCH_rNN.json if it is a bench artifact (any schema version),
    upgraded to the v8 shape; None for the early raw-transcript rounds."""
    try:
        with open(path) as f:
            artifact = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(artifact, dict) or artifact.get("schema") not in SCHEMAS:
        return None
    if artifact["schema"] != "kukeon-bench/v8":
        artifact = dict(artifact)
        artifact.setdefault("replicas", 1)
        artifact.setdefault("kv_page_tokens", 0)
        artifact.setdefault("max_sessions", artifact.get("sessions"))
        lat = ((artifact.get("latency_s") or {}).get("ttft") or {})
        artifact.setdefault("ttft_p95_s", lat.get("p95"))
        artifact.setdefault("handoff_ms_p50", None)
        artifact.setdefault("disagg", None)
        artifact.setdefault("diurnal", None)
        if isinstance(artifact.get("cold_start"), dict):
            artifact["cold_start"] = dict(artifact["cold_start"])
            artifact["cold_start"].setdefault("load_s", None)
        artifact.setdefault("mesh", None)
        artifact.setdefault("program_costs", None)
        artifact.setdefault("mfu", None)
        artifact["schema"] = "kukeon-bench/v8"
    return artifact


def _dig(artifact: dict, path: tuple[str, ...]) -> float | None:
    cur: object = artifact
    for key in path:
        if not isinstance(cur, dict) or cur.get(key) is None:
            return None
        cur = cur[key]
    try:
        return float(cur)  # type: ignore[arg-type]
    except (TypeError, ValueError):
        return None


def find_rounds(directory: str) -> list[tuple[int, str, dict]]:
    """(round number, path, artifact) for every parseable BENCH_rNN.json,
    sorted by round."""
    out = []
    for path in glob.glob(os.path.join(directory, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        artifact = read_artifact(path)
        if artifact is not None:
            out.append((int(m.group(1)), path, artifact))
    return sorted(out)


def compare(prev: dict, new: dict, threshold_pct: float
            ) -> tuple[list[tuple[str, float | None, float | None,
                                  float | None, str]], bool]:
    """Per-metric rows (label, prev, new, delta %, verdict) and whether
    any shared metric regressed past the threshold. A metric missing on
    either side is reported but never a regression — early artifacts
    lack fields later rounds added."""
    rows = []
    regressed = False
    for label, path, direction in METRICS:
        a, b = _dig(prev, path), _dig(new, path)
        if a is None or b is None:
            rows.append((label, a, b, None, "n/a"))
            continue
        if a == 0:
            rows.append((label, a, b, None, "n/a"))
            continue
        delta_pct = (b - a) / abs(a) * 100.0
        worse = -delta_pct * direction
        if worse > threshold_pct:
            rows.append((label, a, b, delta_pct, "REGRESSION"))
            regressed = True
        elif -worse > threshold_pct:
            rows.append((label, a, b, delta_pct, "improved"))
        else:
            rows.append((label, a, b, delta_pct, "ok"))
    return rows, regressed


def _fmt(v: float | None) -> str:
    if v is None:
        return "-"
    if abs(v) >= 1e6 and v == int(v):
        return f"{v:.3e}"
    return f"{v:.4g}"


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python tools/bench_compare.py",
        description="diff the two newest bench trajectory artifacts")
    parser.add_argument("artifacts", nargs="*",
                        help="explicit OLD NEW artifact paths (default: "
                             "the two newest BENCH_rNN.json)")
    parser.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="directory holding BENCH_rNN.json (default: the repo root)")
    parser.add_argument("--threshold", type=float, default=10.0,
                        help="regression tolerance in percent "
                             "(default 10)")
    args = parser.parse_args(argv)

    if args.artifacts and len(args.artifacts) != 2:
        print("error: give exactly two artifact paths (old new), or none",
              file=sys.stderr)
        return 2
    if args.artifacts:
        pair = []
        for path in args.artifacts:
            artifact = read_artifact(path)
            if artifact is None:
                print(f"error: {path} is not a bench artifact "
                      f"(schema {SCHEMAS})", file=sys.stderr)
                return 2
            pair.append((path, artifact))
        (prev_path, prev), (new_path, new) = pair
    else:
        rounds = find_rounds(args.dir)
        if len(rounds) < 2:
            print(f"bench_compare: {len(rounds)} comparable artifact(s) "
                  f"under {args.dir} — need two rounds to diff; nothing "
                  "to do")
            return 0
        (_n0, prev_path, prev), (_n1, new_path, new) = rounds[-2:]

    if prev.get("backend") != new.get("backend"):
        print(f"bench_compare: NOTE backend changed "
              f"{prev.get('backend')!r} -> {new.get('backend')!r} — "
              "deltas compare different hardware")
    print(f"bench_compare: {os.path.basename(prev_path)} -> "
          f"{os.path.basename(new_path)} "
          f"(threshold {args.threshold:g}%)")
    rows, regressed = compare(prev, new, args.threshold)
    fmt = "{:<20} {:>12} {:>12} {:>9} {}"
    print(fmt.format("METRIC", "PREV", "NEW", "DELTA", "VERDICT"))
    for label, a, b, delta, verdict in rows:
        print(fmt.format(label, _fmt(a), _fmt(b),
                         "-" if delta is None else f"{delta:+.1f}%",
                         verdict))
    if regressed:
        print(f"bench_compare: regression past {args.threshold:g}% — "
              "inspect the newest round before shipping")
        return 1
    print("bench_compare: no regression past the threshold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
