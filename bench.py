"""Benchmark: flagship 8B-class agent serving + Session cold-start.

BASELINE.json north star: 4 concurrent coding-agent sessions on a v5e-8
serving Llama-3-8B at >=1500 aggregate tok/s, p50 Session cold-start <90s.
This harness measures both, scaled to the chips actually present, and is
iso-model: on TPU the served model IS the 8B shape (int8 weights-only
quantization — ~8 GB — fits a single 16 GB v5e chip), so ``vs_baseline``
compares like with like (8B throughput vs the pro-rata 8B target,
1500 * n_chips / 8).

Pipeline (TPU):
  1. synthesize an 8B HF-hub-layout checkpoint (sharded safetensors +
     config.json + tokenizer.json) — no network egress, so weights are
     random at the real shapes; every serving byte still flows through the
     exact code a downloaded checkpoint would (models/checkpoints.py);
  2. stream-quantize it to the kukeon int8 format (cached);
  3. serve it through ServingEngine (continuous batching, chunked decode)
     with the checkpoint's real BPE tokenizer — measured in a subprocess so
     the orchestrator never holds the chip (libtpu is single-process);
  4. cold-start: 3x [fresh daemon -> `kuke apply` model-cell manifest ->
     first /v1/health 200], p50 (VERDICT r2/r3 item 2). The health endpoint
     answers only after weight load + compile warmup, so this is the full
     boot cost an agent session would see.

Prints exactly ONE JSON line:
  {"metric", "value" (tok/s), "unit", "vs_baseline", "trials",
   "cold_start": {"p50_s", "target_s", "runs_s"}}

CPU hosts run a tiny-model smoke of the same two phases.
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request
import uuid

REPO = os.path.dirname(os.path.abspath(__file__))
CACHE = os.environ.get("KUKEON_BENCH_CACHE", "/tmp/kukeon-bench")
COLD_START_TARGET_S = 90.0


def _log(msg: str) -> None:
    print(f"bench: {msg}", file=sys.stderr, flush=True)


def subprocess_env() -> dict:
    """Env for child processes. When the caller forces JAX_PLATFORMS=cpu,
    strip TPU-plugin sitecustomize dirs from PYTHONPATH — such plugins
    pre-import jax and would ignore the env var (see tests/conftest.py) —
    and put the repo on the path."""
    env = dict(os.environ)
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    if env.get("JAX_PLATFORMS") == "cpu":
        parts = [p for p in parts if "axon" not in p]
    if REPO not in parts:
        parts.insert(0, REPO)
    env["PYTHONPATH"] = os.pathsep.join(parts)
    return env


def detect_backend() -> tuple[str, int]:
    """Backend + device count, probed in a throwaway subprocess so this
    orchestrator process never initializes (and then holds) the TPU.

    A hung/unreachable TPU runtime (tunnel down, chip wedged) degrades to
    the CPU smoke instead of failing the whole benchmark: a measured CPU
    line beats no line. The probe includes a real device transfer — a
    wedged runtime initializes fine and then blocks the first device_put
    forever (observed r4/r5), which would otherwise burn the entire
    serve-phase timeout before the fallback could fire."""

    def probe() -> tuple[str, int] | str:
        try:
            out = subprocess.run(
                [sys.executable, "-c",
                 "import jax, numpy; "
                 "d = jax.device_put(numpy.ones((16, 1024, 1024), numpy.int8)); "
                 "jax.block_until_ready(d); "
                 "print(jax.default_backend(), len(jax.devices()))"],
                capture_output=True, text=True, timeout=300, cwd=REPO,
                env=subprocess_env(),
            )
        except subprocess.TimeoutExpired:
            return "probe timed out (runtime unreachable or wedged)"
        if out.returncode != 0:
            return f"probe failed:\n{out.stderr[-1500:]}"
        backend, n = out.stdout.split()[-2:]
        return backend, int(n)

    got = probe()
    if isinstance(got, tuple):
        return got
    _log(f"backend {got}")
    # Forced-CPU fallback. The env mutation is load-bearing: every later
    # child (serve phase, cold-start daemons) builds its env from
    # os.environ via subprocess_env().
    os.environ["JAX_PLATFORMS"] = "cpu"
    got = probe()
    if isinstance(got, tuple):
        return got
    raise RuntimeError(f"cpu fallback {got}")


# --- checkpoint prep (host-only, no TPU) -------------------------------------

def ensure_quantized_8b() -> str:
    """Synthesize the 8B HF checkpoint and its int8 quantized form (both
    cached under CACHE); returns the quantized checkpoint dir."""
    sys.path.insert(0, REPO)
    from kukeon_tpu.models import checkpoints, hf_convert, llama

    qdir = os.path.join(CACHE, "llama3-8b-int8")
    if checkpoints.is_quantized_checkpoint(qdir):
        return qdir
    hf_dir = os.path.join(CACHE, "llama3-8b-hf")
    cfg = llama.llama3_8b()
    t0 = time.monotonic()
    _log("synthesizing 8B HF checkpoint (one-time, ~16 GB)...")
    checkpoints.synthesize_hf_checkpoint(hf_dir, cfg)
    _log(f"synthesized in {time.monotonic() - t0:.0f}s; stream-quantizing to int8...")
    t0 = time.monotonic()
    params, cfg = hf_convert.load_params_quantized(hf_dir)
    checkpoints.save_quantized(qdir, params, cfg)
    # The serving cell wants the tokenizer next to the weights it loads.
    import shutil

    shutil.copy(os.path.join(hf_dir, "tokenizer.json"),
                os.path.join(qdir, "tokenizer.json"))
    _log(f"quantized in {time.monotonic() - t0:.0f}s -> {qdir}")
    return qdir


# --- serve phase (runs in its own process; owns the chip) ---------------------

def phase_serve(args) -> None:
    import numpy as np

    sys.path.insert(0, REPO)
    import jax

    from kukeon_tpu.models import checkpoints, llama
    from kukeon_tpu.parallel import auto_mesh_shape, make_mesh
    from kukeon_tpu.serving import SamplingParams, ServingEngine
    from kukeon_tpu.serving.tokenizer import load_tokenizer

    backend = jax.default_backend()
    n_chips = len(jax.devices())
    if args.chips:
        # Sharding-layout arm: exactly N chips, all on the tensor axis
        # (over-asking the host fails loudly in serving_mesh).
        from kukeon_tpu.parallel import serving_mesh

        mesh = serving_mesh(args.chips)
    else:
        shape = auto_mesh_shape(n_chips)
        mesh = make_mesh(data=shape["data"], tensor=shape["tensor"])

    if args.checkpoint:
        params, cfg = checkpoints.load_quantized(args.checkpoint)
        tokenizer = load_tokenizer(args.checkpoint)
        model_id, model_name = "llama3-8b", "llama3-8b (int8)"
        sessions, prompt_len, new_tokens, max_seq = 4, 128, 128, 1024
    else:
        cfg = llama.llama_tiny()
        params = llama.init_params(jax.random.key(0), cfg)
        tokenizer = None
        model_id, model_name = "tiny", "tiny (cpu smoke)"
        sessions, prompt_len, new_tokens, max_seq = 2, 32, 16, 128

    buckets = None
    if args.prefill_buckets:
        buckets = tuple(int(b) for b in args.prefill_buckets.split(","))
    engine = ServingEngine(
        cfg, params, mesh, num_slots=sessions, max_seq_len=max_seq,
        decode_chunk=args.decode_chunk, kv_cache_int8=args.kv_int8,
        prefill_buckets=buckets, kv_page_tokens=args.kv_page_tokens or 0,
        # auto = the engine's divisibility default (then the tune profile);
        # on/off pin the KV-pool layout for a sharding-sweep arm.
        kv_shard={"auto": None, "on": True, "off": False}[args.kv_shard],
    )

    _LAT_HISTS = (("ttft", "kukeon_engine_ttft_seconds"),
                  ("inter_token", "kukeon_engine_inter_token_seconds"),
                  ("e2e", "kukeon_engine_e2e_seconds"))

    def latency_snapshot():
        return {name: engine.registry.get(name).snapshot()[0]
                for _s, name in _LAT_HISTS}

    def latency_percentiles(base):
        """p50/p95/p99 TTFT, inter-token, and e2e latency read from the
        engine's OWN obs histograms — the perf trajectory is measured by
        the product's instruments, not a harness-side stopwatch. Counts
        are deltas against the post-warmup snapshot so the warmup
        request's compile time never pollutes the percentiles."""
        from kukeon_tpu.obs import percentile_from_counts

        out = {}
        for short, name in _LAT_HISTS:
            h = engine.registry.get(name)
            counts = [c - b for c, b in zip(h.snapshot()[0], base[name])]
            ps = {f"p{int(q * 100)}": percentile_from_counts(
                h.buckets, counts, q) for q in (0.5, 0.95, 0.99)}
            if all(v is not None for v in ps.values()):
                out[short] = {k: round(v, 5) for k, v in ps.items()}
        return out

    rng = np.random.default_rng(0)
    if tokenizer is not None:
        # Real-tokenizer prompts: encode an agent-ish request, tile to the
        # measured prompt length.
        base = tokenizer.encode(
            "You are a coding agent. Read the build failure below and "
            "produce a minimal patch.\n\ndef main(argv):\n    return run(argv)\n"
        )
        prompts = []
        for i in range(sessions):
            ids = (base * (prompt_len // len(base) + 1))[:prompt_len]
            prompts.append(np.asarray(ids, np.int32))
    else:
        prompts = [
            rng.integers(1, cfg.vocab_size, size=prompt_len).astype(np.int32)
            for _ in range(sessions)
        ]
    sp = SamplingParams(max_new_tokens=new_tokens)

    # AOT precompile first: it feeds ProgramTimers the static
    # cost-analysis FLOPs/bytes (the denominators behind the per-program
    # MFU / membw gauges and the artifact's program_costs section) and
    # pre-warms the compile cache the warmup dispatch then hits.
    engine.precompile((prompt_len,))
    engine.warmup(prompt_len, sp)
    # Warmup's single pass overlaps the tail of the async param transfer;
    # measuring before every byte lands would charge transfer time to
    # trial 1 (r5: first trial measured 2 tok/s vs 261 steady-state).
    jax.block_until_ready(engine.params)
    _log("warmup done; measuring...")
    lat_base = latency_snapshot()

    # The chip link can jitter; median of several trials.
    trials = 1 if backend == "cpu" else 3
    rates = []
    for _ in range(trials):
        t0 = time.monotonic()
        reqs = [engine.submit(p, sp) for p in prompts]
        while not all(r.done.is_set() for r in reqs):
            engine.step()
        dt = time.monotonic() - t0
        total_tokens = sum(len(r.generated) for r in reqs)
        rates.append(total_tokens / dt)
    rates.sort()
    # Device-layer facts ride along with every serve measurement: compile
    # counts by program (an unexpected steady-state retrace shows up as a
    # moving decode count between artifacts) and peak HBM (headroom for
    # slot-count / context-length tuning). Both read from the engine's own
    # obs instruments; peak is None on backends without memory stats (CPU).
    compiles = {p: engine.compiles.count(p)
                for p in ("prefill", "insert", "decode")}
    # Roofline ride-along (v8): per-program dispatch counts, settled wall
    # time, token totals, and the static FLOPs/bytes precompile captured,
    # plus the headline MFU (the busiest program's model-FLOPs
    # utilization). All read from the engine's own ProgramTimers — the
    # same numbers /metrics exposes as kukeon_program_* gauges.
    engine.timers.settle()
    program_costs = engine.timers.snapshot()
    mfu = max((c.get("mfu") or 0.0) for c in program_costs.values()) \
        if program_costs else 0.0
    peak_hbm = None
    for d in jax.devices():
        try:
            ms = d.memory_stats()
        except Exception:  # noqa: BLE001
            ms = None
        if ms and "peak_bytes_in_use" in ms:
            peak_hbm = max(peak_hbm or 0, int(ms["peak_bytes_in_use"]))
    print(json.dumps({
        "backend": backend,
        "n_chips": n_chips,
        "model": model_name,
        "model_id": model_id,
        "sessions": sessions,
        "tok_per_s": rates[len(rates) // 2],
        "trials": [round(r, 1) for r in rates],
        "latency_s": latency_percentiles(lat_base),
        "compiles": compiles,
        "program_costs": program_costs,
        # Six digits, matching timers.snapshot(): a CPU-smoke MFU is
        # O(1e-5) and a 4-digit round would flatten it to a lying zero.
        "mfu": round(mfu, 6),
        "peak_hbm_bytes": peak_hbm,
        "kv_page_tokens": engine.page_tokens,
        # The mesh this measurement ran on: chips, the tensor-axis size,
        # and whether the KV pool actually sharded over it (the engine may
        # replicate on a head-divisibility miss even when asked to shard).
        "mesh": {
            "chips": int(mesh.size),
            "tensor": int(mesh.shape["tensor"]),
            "kv_sharded": bool(any(engine._cache_shardings()[0].spec)),
        },
        "config": {
            "decode_chunk": engine.decode_chunk,
            "kv_cache_int8": engine.kv_cache_int8,
            "prefill_buckets": (list(engine.prefill_buckets)
                                if buckets else None),
            "kv_page_tokens": engine.page_tokens,
            "chips": args.chips,
            "kv_shard": args.kv_shard,
        },
    }), flush=True)


def phase_mixed(args) -> None:
    """Agent-session workload on a FIXED KV HBM budget (the paged-KV
    acceptance bench): bimodal prompt/generation lengths, sessions reusing
    a shared prefix, submitted as one preemption-inducing flood. The same
    workload runs against the legacy contiguous engine and the paged
    engine at equal KV rows, and the line reports max concurrent sessions,
    aggregate tok/s, preemptions, and failures (which must be zero) for
    each arm — the paged engine's win is concurrency at equal HBM, not a
    faster single decode step."""
    import gc

    import numpy as np

    sys.path.insert(0, REPO)
    import jax

    from kukeon_tpu.models import checkpoints, llama
    from kukeon_tpu.parallel import auto_mesh_shape, make_mesh
    from kukeon_tpu.serving import SamplingParams, ServingEngine

    backend = jax.default_backend()
    n_chips = len(jax.devices())
    shape = auto_mesh_shape(n_chips)
    mesh = make_mesh(data=shape["data"], tensor=shape["tensor"])

    if args.checkpoint:
        params, cfg = checkpoints.load_quantized(args.checkpoint)
        model_id = "llama3-8b"
        max_seq, legacy_slots, paged_slots = 1024, 4, 12
        pt = args.kv_page_tokens or 64
        prefix_len, chat_tail, long_tail = 256, 32, 384
        chat_gen, long_gen, n_sessions = 64, 128, 24
    else:
        cfg = llama.llama_tiny()
        params = llama.init_params(jax.random.key(0), cfg)
        model_id = "tiny"
        max_seq, legacy_slots, paged_slots = 128, 2, 4
        pt = args.kv_page_tokens or 16
        prefix_len, chat_tail, long_tail = 64, 8, 40
        chat_gen, long_gen, n_sessions = 32, 24, 24

    # Equal HBM: the paged pool holds exactly the KV rows the legacy
    # engine reserves up front (legacy_slots * max_seq), carved into
    # pages. The paged arm gets more decode slots — slots are scheduling
    # entries there, the pool is what bounds memory.
    kv_rows = legacy_slots * max_seq
    pool_pages = kv_rows // pt

    rng = np.random.default_rng(7)
    prefix = rng.integers(1, cfg.vocab_size, size=prefix_len).astype(np.int32)
    workload = []            # (prompt, max_new_tokens)
    for i in range(n_sessions):
        is_long = i % 2 == 1   # bimodal: half long agent turns, half chatty
        tail = rng.integers(
            1, cfg.vocab_size,
            size=long_tail if is_long else chat_tail).astype(np.int32)
        workload.append((np.concatenate([prefix, tail]),
                         long_gen if is_long else chat_gen))

    def run_arm(kv_page_tokens: int, num_slots: int) -> dict:
        engine = ServingEngine(
            cfg, params, mesh, num_slots=num_slots, max_seq_len=max_seq,
            decode_chunk=args.decode_chunk, kv_cache_int8=args.kv_int8,
            kv_page_tokens=kv_page_tokens,
            kv_pool_pages=pool_pages if kv_page_tokens else None,
        )
        engine.warmup(prefix_len + chat_tail)
        jax.block_until_ready(engine.params)
        # Warm the prefix path before measuring: the first shared-prefix
        # request stores the prefix, the second compiles the extension
        # prefill (gather + suffix-only programs) — steady-state agent
        # serving runs warm, and a compile inside the timed flood would
        # charge one-time cost to the throughput number.
        for p, gen in (workload[0], workload[1], workload[2]):
            r = engine.submit(p, SamplingParams(max_new_tokens=gen),
                              prefix_id="agent")
            while not r.done.is_set():
                engine.step()
        base_preempt = int(engine._m_preempt.value(reason="kv_pressure"))
        base_hits = engine.prefix_hits
        t0 = time.monotonic()
        reqs = [
            engine.submit(p, SamplingParams(max_new_tokens=gen),
                          prefix_id="agent")
            for p, gen in workload
        ]
        max_sessions = 0
        while not all(r.done.is_set() for r in reqs):
            engine.step()
            max_sessions = max(
                max_sessions,
                sum(1 for r in engine._slot_req if r is not None))
        dt = time.monotonic() - t0
        total = sum(len(r.generated) for r in reqs)
        out = {
            "max_sessions": max_sessions,
            "tok_per_s": round(total / dt, 2),
            "tokens": total,
            "wall_s": round(dt, 2),
            "failed": sum(1 for r in reqs if r.error is not None),
            "preemptions": int(engine._m_preempt.value(
                reason="kv_pressure")) - base_preempt,
            "prefix_hits": engine.prefix_hits - base_hits,
            "compiles": {p: engine.compiles.count(p)
                         for p in ("prefill", "insert", "decode")},
        }
        engine.stop()
        del engine
        gc.collect()
        return out

    _log(f"mixed: legacy arm ({legacy_slots} slots, {kv_rows} KV rows)...")
    legacy = run_arm(0, legacy_slots)
    _log(f"mixed legacy: {legacy}")
    _log(f"mixed: paged arm ({paged_slots} slots, {pool_pages} pages of "
         f"{pt})...")
    paged = run_arm(pt, paged_slots)
    _log(f"mixed paged: {paged}")

    line = {
        "metric": (f"mixed agent sessions, {model_id}, {n_sessions} "
                   f"bimodal requests, shared prefix, equal KV HBM "
                   f"({kv_rows} rows), {n_chips} chip(s) [{backend}]"),
        "backend": backend,
        "n_chips": n_chips,
        "model": model_id,
        "kv_page_tokens": pt,
        "kv_pool_pages": pool_pages,
        "arms": {"legacy": legacy, "paged": paged},
        "max_sessions_gain": (round(paged["max_sessions"]
                                    / max(1, legacy["max_sessions"]), 2)),
        "tok_per_s_gain": (round(paged["tok_per_s"]
                                 / max(1e-9, legacy["tok_per_s"]), 3)),
    }
    if backend == "tpu":
        try:
            with open(os.path.join(REPO, "BENCH_TPU_HISTORY.jsonl"), "a") as f:
                f.write(json.dumps({
                    "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                    "note": "mixed agent-session workload", **line,
                }) + "\n")
        except OSError:
            pass
    if args.out:
        serve = {
            "backend": backend, "n_chips": n_chips, "model": model_id,
            "model_id": model_id, "sessions": n_sessions,
            "tok_per_s": paged["tok_per_s"],
            "trials": [paged["tok_per_s"]],
            "kv_page_tokens": pt,
            "max_sessions": paged["max_sessions"],
            "compiles": paged["compiles"],
        }
        write_artifact(args.out, serve, {"mixed": line})
    print(json.dumps(line), flush=True)


def phase_disagg(args) -> None:
    """Disaggregated prefill/decode serving vs mixed co-location at equal
    chips and equal KV HBM (`bench.py --mixed --disagg`): the bimodal
    agent-session flood runs twice through the REAL gateway + HTTP path —
    once against two ``mixed`` replicas, once against a 1-prefill +
    1-decode split with the page-granular KV handoff between them. Both
    arms use identical cells (same slots, same page pool) so the only
    variable is the architecture.

    TTFT is measured CLIENT-side: wall time from POST to the first ndjson
    line of a streaming request — the exact latency the TTFT-p95 SLO
    tracker pages on. The disaggregated arm's first token goes out after
    prefill+transfer, before the request waits for a decode slot; the
    mixed arm's waits for slot seating behind co-located decode — that
    architectural difference is what this phase quantifies. The handoff
    cost itself rides along from the gateway's own
    ``kukeon_handoff_seconds`` histogram."""
    import threading
    from http.server import ThreadingHTTPServer

    import numpy as np

    sys.path.insert(0, REPO)
    import jax

    from kukeon_tpu.gateway.cell import GatewayCell, make_gateway_handler
    from kukeon_tpu.runtime.serving_cell import ServingCell, make_handler

    backend = jax.default_backend()
    n_chips = len(jax.devices())
    # Tiny-model scale on every backend: the layer under test is the
    # serving architecture (routing, handoff, slot queueing), not the
    # matmuls — same rationale as the gateway phase.
    num_slots = 2
    max_seq = 128
    pt = args.kv_page_tokens or 16
    prefix_len, chat_tail, long_tail = 48, 8, 32
    chat_gen, long_gen, n_sessions = 12, 40, 16

    rng = np.random.default_rng(7)
    prefix = [int(x) for x in rng.integers(1, 250, size=prefix_len)]
    workload = []            # (promptTokens, max_new_tokens)
    for i in range(n_sessions):
        is_long = i % 2 == 1   # bimodal: half long agent turns, half chatty
        tail = [int(x) for x in rng.integers(
            1, 250, size=long_tail if is_long else chat_tail)]
        workload.append((prefix + tail,
                         long_gen if is_long else chat_gen))

    def run_arm(roles: tuple) -> dict:
        import http.client

        cells, servers, urls = [], [], []
        for role in roles:
            cell = ServingCell(
                "tiny", num_slots=num_slots, max_seq_len=max_seq,
                checkpoint=None, dtype=None, kv_page_tokens=pt,
                max_pending=512, role=role)
            cell.engine.start()
            cell.mark_ready()
            srv = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(cell))
            threading.Thread(target=srv.serve_forever, daemon=True).start()
            cells.append(cell)
            servers.append(srv)
            urls.append(f"http://127.0.0.1:{srv.server_address[1]}")
        gw = GatewayCell("tiny", urls, poll_interval_s=0.1)
        gw.start()
        gw.router.poll_once()
        gw_srv = ThreadingHTTPServer(("127.0.0.1", 0),
                                     make_gateway_handler(gw))
        threading.Thread(target=gw_srv.serve_forever, daemon=True).start()
        port = gw_srv.server_address[1]

        def post_stream(body: dict):
            """(ttft_s, n_tokens, status, saw_error) for one streaming
            request — TTFT stops at the FIRST ndjson line's arrival."""
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
            t0 = time.monotonic()
            conn.request("POST", "/v1/generate",
                         body=json.dumps({**body, "stream": True}),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            if resp.status != 200:
                resp.read()
                conn.close()
                return None, 0, resp.status, True
            first = resp.readline()
            ttft = time.monotonic() - t0
            rest = resp.read()
            conn.close()
            toks = 0
            err = False
            for ln in (first + rest).splitlines():
                try:
                    rec = json.loads(ln)
                except ValueError:
                    err = True
                    continue
                if "token" in rec:
                    toks += 1
                if "error" in rec:
                    err = True
            return ttft, toks, 200, err

        # Warm the whole path untimed (compiles: both prefill buckets,
        # insert, decode chunks, the prefix-extension program, and — on
        # the disagg arm — the export/import seams), so the timed flood
        # measures architecture, not compilation.
        for prompt, gen in (workload[0], workload[1], workload[2]):
            post_stream({"promptTokens": prompt, "maxNewTokens": gen,
                         "prefixId": "agent"})

        ttfts: list = []
        totals = [0]
        failures = [0]
        lock = threading.Lock()
        t0 = time.monotonic()

        def session(i: int) -> None:
            prompt, gen = workload[i]
            ttft, toks, status, err = post_stream(
                {"promptTokens": prompt, "maxNewTokens": gen,
                 "prefixId": "agent"})
            with lock:
                if status != 200 or err:
                    failures[0] += 1
                if ttft is not None:
                    ttfts.append(ttft)
                totals[0] += toks

        threads = [threading.Thread(target=session, args=(i,))
                   for i in range(n_sessions)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=600)
        wall = time.monotonic() - t0

        ttfts.sort()
        h = gw.registry.get("kukeon_handoff_seconds")
        handoff_p50 = h.percentile(0.5)
        out = {
            "roles": list(roles),
            "ttft_p50_s": round(ttfts[len(ttfts) // 2], 4) if ttfts else None,
            "ttft_p95_s": (round(ttfts[min(len(ttfts) - 1,
                                           int(len(ttfts) * 0.95))], 4)
                           if ttfts else None),
            "tok_per_s": round(totals[0] / wall, 2),
            "tokens": totals[0],
            "wall_s": round(wall, 2),
            "failed": failures[0],
            "handoff_ms_p50": (round(handoff_p50 * 1000, 2)
                               if handoff_p50 is not None else None),
            "handoffs": int(sum(h.snapshot()[0])),
            "handoff_pages": int(gw.registry.get(
                "kukeon_handoff_pages_total").value()),
            "handoff_bytes": int(gw.registry.get(
                "kukeon_handoff_bytes_total").value()),
            "handoff_fallbacks": int(gw.registry.get(
                "kukeon_handoff_fallback_total").value()),
        }
        gw_srv.shutdown()
        gw.stop()
        for srv in servers:
            srv.shutdown()
        for cell in cells:
            cell.engine.stop()
        return out

    _log("disagg: mixed arm (2x mixed)...")
    mixed = run_arm(("mixed", "mixed"))
    _log(f"disagg mixed arm: {mixed}")
    _log("disagg: disaggregated arm (1 prefill + 1 decode)...")
    disagg = run_arm(("prefill", "decode"))
    _log(f"disagg arm: {disagg}")

    line = {
        "metric": (f"disaggregated vs mixed serving, tiny, {n_sessions} "
                   f"bimodal sessions, equal KV HBM, {n_chips} chip(s) "
                   f"[{backend}]"),
        "backend": backend,
        "n_chips": n_chips,
        "model": "tiny",
        "kv_page_tokens": pt,
        "arms": {"mixed": mixed, "disagg": disagg},
        "ttft_p95_gain": (round(mixed["ttft_p95_s"] / disagg["ttft_p95_s"], 3)
                          if mixed["ttft_p95_s"] and disagg["ttft_p95_s"]
                          else None),
        "tok_per_s_ratio": round(
            disagg["tok_per_s"] / max(1e-9, mixed["tok_per_s"]), 3),
        "handoff_ms_p50": disagg["handoff_ms_p50"],
    }
    if backend == "tpu":
        try:
            with open(os.path.join(REPO, "BENCH_TPU_HISTORY.jsonl"), "a") as f:
                f.write(json.dumps({
                    "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                    "note": "disaggregated prefill/decode", **line,
                }) + "\n")
        except OSError:
            pass
    if args.out:
        serve = {
            "backend": backend, "n_chips": n_chips, "model": "tiny",
            "model_id": "tiny", "sessions": n_sessions, "replicas": 2,
            "tok_per_s": disagg["tok_per_s"],
            "trials": [disagg["tok_per_s"]],
            "kv_page_tokens": pt,
            "ttft_p95_s": disagg["ttft_p95_s"],
        }
        write_artifact(args.out, serve, {
            "disagg": line, "handoff_ms_p50": disagg["handoff_ms_p50"]})
    print(json.dumps(line), flush=True)


def phase_gateway(args) -> None:
    """Scale-out serving through the replica gateway (`--replicas N`): N
    in-process serving cells behind a GatewayCell, flooded by concurrent
    prefix-id-carrying sessions. Measures aggregate tok/s THROUGH the proxy
    plus the retry/shed work the routing layer absorbed. The replicas run
    the tiny model on purpose — the layer under test is the gateway
    (routing, affinity, passthrough), not the matmuls, so the number is
    comparable on any backend."""
    import threading
    from http.server import ThreadingHTTPServer

    import numpy as np  # noqa: F401 — serving cell deps

    sys.path.insert(0, REPO)
    import jax

    from kukeon_tpu.gateway.cell import GatewayCell, make_gateway_handler
    from kukeon_tpu.runtime.serving_cell import ServingCell, make_handler

    n = max(2, args.replicas)
    backend = jax.default_backend()
    _log(f"gateway: {n} tiny replicas [{backend}]")
    cells, servers, urls = [], [], []
    for _i in range(n):
        cell = ServingCell("tiny", num_slots=4, max_seq_len=128,
                           checkpoint=None, dtype=None, max_pending=256)
        cell.engine.start()
        cell.mark_ready()
        srv = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(cell))
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        cells.append(cell)
        servers.append(srv)
        urls.append(f"http://127.0.0.1:{srv.server_address[1]}")
    gw = GatewayCell("tiny", urls, poll_interval_s=0.1)
    gw.start()
    gw_srv = ThreadingHTTPServer(("127.0.0.1", 0), make_gateway_handler(gw))
    threading.Thread(target=gw_srv.serve_forever, daemon=True).start()
    gw.router.poll_once()

    sessions = 2 * n
    per_session = 6
    new_tokens = 16
    tokens = [0]
    statuses: dict[int, int] = {}
    lock = threading.Lock()
    t0 = time.monotonic()

    def session(i: int) -> None:
        import http.client

        for _turn in range(per_session):
            conn = http.client.HTTPConnection(
                "127.0.0.1", gw_srv.server_address[1], timeout=120)
            conn.request("POST", "/v1/generate", body=json.dumps({
                "prompt": f"session {i} turn", "maxNewTokens": new_tokens,
                "prefixId": f"sess-{i}"}),
                headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = resp.read()
            conn.close()
            with lock:
                statuses[resp.status] = statuses.get(resp.status, 0) + 1
                if resp.status == 200:
                    tokens[0] += json.loads(body).get("numTokens", 0)

    threads = [threading.Thread(target=session, args=(i,))
               for i in range(sessions)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=600)
    dt = time.monotonic() - t0

    total = sum(statuses.values())
    retries = int(sum(v for _l, v in gw.registry.get(
        "kukeon_gateway_retries_total").samples()))
    result = {
        "metric": f"gateway aggregate tok/s, {n} replicas, "
                  f"{sessions} sessions, tiny [{backend}]",
        "backend": backend,
        "model": "tiny",
        "model_id": "tiny",
        "n_chips": len(jax.devices()),
        "replicas": n,
        "sessions": sessions,
        "tok_per_s": round(tokens[0] / dt, 2),
        "requests": total,
        "retry_rate": round(retries / max(total, 1), 4),
        "shed": int(gw.registry.get("kukeon_gateway_shed_total").value()),
        "statuses": {str(k): v for k, v in sorted(statuses.items())},
        "trials": [round(tokens[0] / dt, 1)],
    }
    gw_srv.shutdown()
    gw.stop()
    for srv in servers:
        srv.shutdown()
    for cell in cells:
        cell.engine.stop()
    if args.out:
        write_artifact(args.out, result, result)
    print(json.dumps(result), flush=True)


def phase_diurnal(args) -> None:
    """Diurnal traffic ramp through the replica gateway (`--diurnal`):
    tiny replicas behind a GatewayCell, driven by an open-loop arrival
    schedule that triples from night to peak and falls past the trough.
    The replicas are deliberately sized so the peak overruns their
    admission queues — the measurement is the gateway's SPILLOVER
    contract (an all-shed storm becomes client latency, never a
    client-visible 429) plus per-stage achieved throughput and client-
    side p95, the workload shape the FleetScaler's reconcile loop is
    built for (kukeon-bench/v5 `diurnal` section)."""
    import threading
    from http.server import ThreadingHTTPServer

    sys.path.insert(0, REPO)
    import jax

    from kukeon_tpu.gateway.cell import GatewayCell, make_gateway_handler
    from kukeon_tpu.runtime.serving_cell import ServingCell, make_handler

    n = max(2, args.replicas)
    backend = jax.default_backend()
    stage_s = float(os.environ.get("KUKEON_BENCH_DIURNAL_STAGE_S", "5"))
    _log(f"diurnal: {n} tiny replicas, {stage_s:.0f}s stages [{backend}]")
    cells, servers, urls = [], [], []
    for _i in range(n):
        # Small slots + shallow admission queue: the peak stage must be
        # able to shed, or the spillover path under test never runs.
        cell = ServingCell("tiny", num_slots=2, max_seq_len=128,
                           checkpoint=None, dtype=None, max_pending=4)
        cell.engine.start()
        cell.mark_ready()
        srv = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(cell))
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        cells.append(cell)
        servers.append(srv)
        urls.append(f"http://127.0.0.1:{srv.server_address[1]}")
    gw = GatewayCell("tiny", urls, poll_interval_s=0.1,
                     spill_max_wait_s=30.0)
    gw.start()
    gw_srv = ThreadingHTTPServer(("127.0.0.1", 0), make_gateway_handler(gw))
    threading.Thread(target=gw_srv.serve_forever, daemon=True).start()
    gw.router.poll_once()
    gport = gw_srv.server_address[1]

    stages = (("night", 4.0), ("peak", 12.0), ("trough", 2.0))   # req/s
    tokens = [0]
    lock = threading.Lock()
    t_run0 = time.monotonic()

    def one_request(i: int, rows: list) -> None:
        import http.client

        t0 = time.monotonic()
        status = None
        try:
            conn = http.client.HTTPConnection("127.0.0.1", gport,
                                              timeout=120)
            conn.request("POST", "/v1/generate", body=json.dumps({
                "prompt": f"turn {i}", "maxNewTokens": 8,
                "prefixId": f"sess-{i % 16}", "deadlineS": 60}),
                headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = resp.read()
            conn.close()
            status = resp.status
            if status == 200:
                with lock:
                    tokens[0] += json.loads(body).get("numTokens", 0)
        except Exception:  # noqa: BLE001 — a transport error is a data point
            status = -1
        with lock:
            rows.append((status, time.monotonic() - t0))

    stage_results = []
    for name, rate in stages:
        rows: list = []
        threads = []
        t_end = time.monotonic() + stage_s
        i = 0
        while time.monotonic() < t_end:
            th = threading.Thread(target=one_request, args=(i, rows))
            th.start()
            threads.append(th)
            i += 1
            time.sleep(1.0 / rate)
        for th in threads:
            th.join(timeout=300)
        lat = sorted(t for s, t in rows if s == 200)
        stage_results.append({
            "stage": name, "target_rps": rate, "requests": len(rows),
            "qps": round(len(rows) / stage_s, 2),
            "p95_s": (round(lat[int(0.95 * (len(lat) - 1))], 4)
                      if lat else None),
            "statuses": {str(k): sum(1 for s, _t in rows if s == k)
                         for k in sorted({s for s, _t in rows})},
        })
        _log(f"diurnal stage {name}: {json.dumps(stage_results[-1])}")
    dt = time.monotonic() - t_run0

    spill = {k: int(gw.registry.get("kukeon_gateway_spill_total").value(
        outcome=k)) for k in ("recovered", "timeout", "overflow", "fault")}
    total = sum(r["requests"] for r in stage_results)
    failed = sum(v for r in stage_results
                 for s, v in r["statuses"].items() if s != "200")
    diurnal = {
        "stages": stage_results,
        "spill": spill,
        "peak_p95_s": stage_results[1]["p95_s"],
        "requests": total,
        "failed": failed,
    }
    serve = {
        "metric": f"diurnal ramp through the gateway, {n} replicas, "
                  f"tiny [{backend}]",
        "backend": backend, "model": "tiny", "model_id": "tiny",
        "n_chips": len(jax.devices()), "replicas": n,
        "sessions": 16, "max_sessions": 16,
        "tok_per_s": round(tokens[0] / dt, 2),
        "trials": [round(tokens[0] / dt, 1)],
    }
    result = {**serve, "diurnal": diurnal}
    gw_srv.shutdown()
    gw.stop()
    for srv in servers:
        srv.shutdown()
    for cell in cells:
        cell.engine.stop()
    if args.out:
        write_artifact(args.out, serve, result)
    print(json.dumps(result), flush=True)


def phase_embed(args) -> None:
    """Embedding-cell throughput (BASELINE config 5: bge-base embedding
    serving): sequences/s for batched ~128-token inputs."""
    import numpy as np

    sys.path.insert(0, REPO)
    import jax

    from kukeon_tpu.models import bert
    from kukeon_tpu.parallel import auto_mesh_shape, make_mesh
    from kukeon_tpu.serving import EmbeddingEngine

    backend = jax.default_backend()
    n_chips = len(jax.devices())
    shape = auto_mesh_shape(n_chips)
    mesh = make_mesh(data=shape["data"], tensor=shape["tensor"])

    if backend == "cpu":
        cfg, model_name, batch, seq_len, n_batches = (
            bert.bge_tiny(), "bge-tiny (cpu smoke)", 8, 32, 2)
    else:
        cfg, model_name, batch, seq_len, n_batches = (
            bert.bge_base(), "bge-base", 32, 128, 8)
    params = bert.init_params(jax.random.key(0), cfg)
    engine = EmbeddingEngine(cfg, params, mesh, batch_size=batch)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, size=seq_len).astype(np.int32)
               for _ in range(batch)]
    engine.warmup((seq_len,))
    t0 = time.monotonic()
    for _ in range(n_batches):
        vecs = engine.embed_batch(prompts)
    dt = time.monotonic() - t0
    print(json.dumps({
        "backend": backend, "model": model_name, "dim": int(vecs.shape[1]),
        "batch": batch, "seq_len": seq_len,
        "seq_per_s": round(batch * n_batches / dt, 1),
    }), flush=True)


def phase_ab(args) -> None:
    """Perf-lever A/B sweep (VERDICT r4 item 4): decode-chunk {4,16,64} and
    int8-KV on the flagship config, each arm in its own chip-owning
    subprocess. Prints one JSON line with every arm's tok/s and appends it
    to the TPU history. Run as `python bench.py --phase ab`."""
    backend, n_chips = detect_backend()
    _log(f"ab: backend={backend} n_chips={n_chips}")
    qdir = None
    if backend != "cpu":
        qdir = ensure_quantized_8b()
    arms = [
        ("chunk4", ["--decode-chunk", "4"]),
        ("chunk16", ["--decode-chunk", "16"]),
        ("chunk64", ["--decode-chunk", "64"]),
        ("chunk16+kvint8", ["--decode-chunk", "16", "--kv-int8"]),
    ]
    results: dict = {}
    for name, extra in arms:
        cmd = [sys.executable, os.path.abspath(__file__), "--phase", "serve"] + extra
        if qdir:
            cmd += ["--checkpoint", qdir]
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=2400, cwd=REPO, env=subprocess_env())
        except subprocess.TimeoutExpired:
            _log(f"ab arm {name}: timed out")
            results[name] = None
            continue
        if out.returncode != 0:
            _log(f"ab arm {name}: rc={out.returncode}\n{out.stderr[-1200:]}")
            results[name] = None
            continue
        serve = json.loads(out.stdout.strip().splitlines()[-1])
        results[name] = {"tok_per_s": round(serve["tok_per_s"], 2),
                         "trials": serve["trials"],
                         "latency_s": serve.get("latency_s")}
        _log(f"ab arm {name}: {results[name]}")
    line = {
        "metric": f"decode-chunk/kv-int8 A/B, 8B int8, {n_chips} chip(s) [{backend}]",
        "arms": results,
        "backend": backend,
    }
    if backend == "tpu":
        try:
            with open(os.path.join(REPO, "BENCH_TPU_HISTORY.jsonl"), "a") as f:
                f.write(json.dumps({
                    "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                    "note": "A/B sweep", **line,
                }) + "\n")
        except OSError:
            pass
    print(json.dumps(line))


def phase_autotune(args) -> None:
    """Autotune sweep (the tentpole of the decode roofline campaign):
    decode-chunk × int8-KV × prefill-bucket arms, each measured by the
    serve phase in its own chip-owning subprocess, winner persisted to the
    serving tune profile (~/.kuke/serving_tune.json, KUKEON_TUNE_PATH to
    override) keyed by model+backend+chip-count. ServingEngine/ServingCell
    consult that profile at boot, so one sweep permanently configures
    production serving. Run as `python bench.py --autotune`; works on the
    CPU smoke when no TPU is reachable (the profile then keys as cpu and
    never leaks into TPU serving)."""
    backend, n_chips = detect_backend()
    _log(f"autotune: backend={backend} n_chips={n_chips}")
    qdir = None
    model_id = "tiny"
    if backend != "cpu":
        qdir = ensure_quantized_8b()
        model_id = "llama3-8b"

    # Arm grid. CPU smoke keeps it small (each arm boots a fresh engine);
    # TPU sweeps the full chunk ladder. The coarse-bucket arm measures
    # whether fewer/larger prefill buckets (fewer compiles, more padded
    # prefill compute) beat the default ladder for this workload.
    chunks = (4, 16, 64) if backend == "tpu" else (4, 16)
    coarse = "256,1024,4096" if backend == "tpu" else "64,256"
    arms: list[tuple[str, dict]] = []
    for c in chunks:
        for kv in (False, True):
            arms.append((f"chunk{c}" + ("+kvint8" if kv else ""),
                         {"decode_chunk": c, "kv_int8": kv,
                          "prefill_buckets": None}))
    arms.append((f"chunk{chunks[-1]}+coarse-buckets",
                 {"decode_chunk": chunks[-1], "kv_int8": False,
                  "prefill_buckets": coarse}))
    # Paged-KV arms: page size is an autotune lever like the others. The
    # serve phase sizes the pool to its slot count, so these arms measure
    # the gather/scatter overhead of the paged programs at steady state;
    # the concurrency upside at equal HBM is phase_mixed's measurement.
    for pt in ((64, 128) if backend == "tpu" else (16,)):
        arms.append((f"chunk{chunks[-1]}+paged{pt}",
                     {"decode_chunk": chunks[-1], "kv_int8": False,
                      "prefill_buckets": None, "kv_page_tokens": pt}))
    # Sharding-layout arms (the multi-chip sweep): every tensor-axis size
    # this host can factor (divisors of the chip count, capped at one ICI
    # ring) × KV pool sharded vs replicated. Size 1 is the baseline the
    # arms above already measure; a single-chip host grows no arms.
    for ms in (d for d in (2, 4, 8) if d <= n_chips and n_chips % d == 0):
        for kv in ("on", "off"):
            arms.append(
                (f"chunk{chunks[-1]}+mesh{ms}"
                 + ("+kvshard" if kv == "on" else "+kvrepl"),
                 {"decode_chunk": chunks[-1], "kv_int8": False,
                  "prefill_buckets": None, "chips": ms, "kv_shard": kv}))

    results: dict = {}
    best_name, best_cfg, best_rate = None, None, -1.0
    for name, cfg in arms:
        cmd = [sys.executable, os.path.abspath(__file__), "--phase", "serve",
               "--decode-chunk", str(cfg["decode_chunk"])]
        if cfg["kv_int8"]:
            cmd += ["--kv-int8"]
        if cfg["prefill_buckets"]:
            cmd += ["--prefill-buckets", cfg["prefill_buckets"]]
        if cfg.get("kv_page_tokens"):
            cmd += ["--kv-page-tokens", str(cfg["kv_page_tokens"])]
        if cfg.get("chips"):
            cmd += ["--chips", str(cfg["chips"]),
                    "--kv-shard", cfg.get("kv_shard", "auto")]
        if qdir:
            cmd += ["--checkpoint", qdir]
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=2400, cwd=REPO, env=subprocess_env())
        except subprocess.TimeoutExpired:
            _log(f"autotune arm {name}: timed out")
            results[name] = None
            continue
        if out.returncode != 0:
            _log(f"autotune arm {name}: rc={out.returncode}\n{out.stderr[-1200:]}")
            results[name] = None
            continue
        serve = json.loads(out.stdout.strip().splitlines()[-1])
        rate = float(serve["tok_per_s"])
        # Every arm is scored with the same product-instrument percentiles
        # the serve phase reports (p50/p95/p99 TTFT / inter-token / e2e):
        # the sweep record shows what each lever costs in tail latency,
        # not just what it buys in throughput.
        results[name] = {"tok_per_s": round(rate, 2),
                         "trials": serve["trials"],
                         "latency_s": serve.get("latency_s"),
                         "mesh": serve.get("mesh")}
        _log(f"autotune arm {name}: {results[name]}")
        if rate > best_rate:
            best_name, best_cfg, best_rate = name, cfg, rate

    line: dict = {
        "metric": f"autotune sweep, {model_id}, {n_chips} chip(s) [{backend}]",
        "arms": results,
        "backend": backend,
        "model": model_id,
    }
    if best_cfg is not None:
        sys.path.insert(0, REPO)
        from kukeon_tpu.serving import tuning

        buckets = (tuple(int(b) for b in best_cfg["prefill_buckets"].split(","))
                   if best_cfg["prefill_buckets"] else None)
        path = tuning.save(model_id, backend, n_chips, tuning.ServingTune(
            decode_chunk=best_cfg["decode_chunk"],
            kv_cache_int8=best_cfg["kv_int8"],
            prefill_buckets=buckets,
            kv_page_tokens=best_cfg.get("kv_page_tokens"),
            # Sharding layout of the winner: absent fields keep whatever
            # the cell's chip grant / divisibility default dictates.
            mesh_tensor=best_cfg.get("chips"),
            kv_shard={"on": True, "off": False}.get(
                best_cfg.get("kv_shard")),
            tok_per_s=best_rate,
        ))
        line["best"] = {"arm": best_name, "tok_per_s": round(best_rate, 2)}
        line["profile"] = {"path": path,
                           "key": tuning.profile_key(model_id, backend, n_chips)}
        _log(f"autotune: winner {best_name} ({best_rate:.1f} tok/s) -> {path}")
    else:
        line["error"] = "every arm failed; profile not written"
    if backend == "tpu":
        try:
            with open(os.path.join(REPO, "BENCH_TPU_HISTORY.jsonl"), "a") as f:
                f.write(json.dumps({
                    "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                    "note": "autotune sweep", **line,
                }) + "\n")
        except OSError:
            pass
    print(json.dumps(line))


def phase_profile_layers(args) -> None:
    """Per-layer cost profiling (obs/profile.profile_layers): lower every
    transformer component (embed, each layer, head) individually at the
    prefill and decode shapes, record XLA cost-analysis FLOPs/bytes plus
    measured wall time, and persist the profile next to the serving tune
    keyed ``model|backend|n_chips`` — `kuke profile layers` renders it;
    the pipeline-split planner (ROADMAP item 2) consumes it. An armed
    ``profile.layers`` fault degrades to recorded per-component error
    entries and skips persistence — a clean reported failure, never a
    crashed bench."""
    sys.path.insert(0, REPO)
    import jax

    from kukeon_tpu.models import checkpoints, llama
    from kukeon_tpu.obs import profile as obs_profile
    from kukeon_tpu.parallel import auto_mesh_shape, make_mesh
    from kukeon_tpu.serving import tuning

    backend = jax.default_backend()
    n_chips = len(jax.devices())
    if args.checkpoint:
        params, cfg = checkpoints.load_quantized(args.checkpoint)
        model_id = "llama3-8b"
        prefill_len, decode_batch = 128, 4
    else:
        cfg = llama.llama_tiny()
        params = llama.init_params(jax.random.key(0), cfg)
        model_id = "tiny"
        prefill_len, decode_batch = 32, 2
    shape = auto_mesh_shape(n_chips)
    mesh = make_mesh(data=shape["data"], tensor=shape["tensor"])
    _log(f"profile-layers: {model_id} [{backend}] "
         f"prefill_len={prefill_len} decode_batch={decode_batch}")
    prof = obs_profile.profile_layers(
        params, cfg, mesh, prefill_len=prefill_len,
        decode_batch=decode_batch)
    key = tuning.profile_key(model_id, backend, n_chips)
    prof["key"] = key
    line = {"metric": f"per-layer cost profile, {model_id},"
                      f" {n_chips} chip(s) [{backend}]",
            "key": key,
            "num_layers": prof.get("num_layers"),
            "model_flops": prof.get("model_flops"),
            "model_bytes": prof.get("model_bytes"),
            "errors": prof.get("errors", 0)}
    if prof.get("errors"):
        line["failed"] = [c.get("name") for c in prof.get("components", ())
                          if c.get("error")]
        _log(f"profile-layers: {prof['errors']} component(s) failed; "
             "profile not persisted")
    else:
        line["path"] = tuning.save_layer_profile(
            model_id, backend, n_chips, prof)
        _log(f"profile-layers: persisted -> {line['path']}")
    print(json.dumps(line), flush=True)


# --- cold-start phase ---------------------------------------------------------

def _tail_file(path: str, limit: int = 2500) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - limit))
            return f.read().decode(errors="replace")
    except OSError:
        return f"<unreadable: {path}>"


def _dump_evidence(run_path: str, daemon_log: str, cli: list[str],
                   socket_path: str, env: dict, run: int) -> None:
    """Preserve the crime scene on stderr BEFORE cleanup destroys it
    (VERDICT r4 weak 2: r4's cold-start failure was undiagnosable because
    rmtree ran before anything read the model-server log; the reference's
    e2e harness preserves daemon evidence — harness_daemon_test.go:26-60)."""
    import glob

    _log(f"=== cold-start run {run} evidence ===")
    try:
        got = subprocess.run(
            cli + ["--socket", socket_path, "--run-path", run_path,
                   "get", "cell", "llm", "--json"],
            env=env, capture_output=True, text=True, timeout=30,
        )
        _log("kuke get cell llm --json:\n" + (got.stdout or got.stderr)[-3000:])
    except Exception as e:  # noqa: BLE001 — evidence is best-effort
        _log(f"kuke get failed: {e}")
    for pattern, label in (
        (os.path.join(run_path, "**", "model-server", "container.log"),
         "model-server container.log"),
        (daemon_log, "daemon log"),
    ):
        paths = glob.glob(pattern, recursive=True) if "*" in pattern else [pattern]
        for p in paths:
            _log(f"--- {label} tail ({p}) ---\n{_tail_file(p)}")
    _log(f"=== end evidence (run {run}) ===")


def _cold_start_phases(port: int) -> dict:
    """Phase breakdown from the freshly-booted cell's own cold-start
    gauges (kukeon_cold_start_phase_seconds{phase=} + the total): the
    artifact records WHERE the boot time went (imports, init, compile,
    warmup, serve), not just the total — the ROADMAP item 4 attack
    surface. Best-effort: an older cell without the gauges yields {}."""
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
            text = r.read().decode()
        from kukeon_tpu.obs import federate as fed

        fams = fed.parse(text)
        out: dict = {}
        fam = fams.get("kukeon_cold_start_phase_seconds")
        if fam is not None:
            for _n, labels, value in fam.samples:
                if labels.get("phase"):
                    # 3 decimals: the disk/cast/upload load sub-phases are
                    # millisecond-scale on the CPU tier and must survive.
                    out[labels["phase"]] = round(float(value), 3)
        total = fams.get("kukeon_cold_start_seconds")
        if total is not None and total.samples:
            out["total"] = round(float(total.samples[0][2]), 3)
        return out
    except Exception:  # noqa: BLE001 — phases are evidence, never a failure
        return {}


def measure_cold_starts(model: str, checkpoint: str | None, runs: int,
                        chips: str
                        ) -> tuple[list[float], list[str], list[dict]]:
    """N x [fresh daemon -> kuke apply model-cell manifest -> first
    /v1/health 200]. The daemon and model server are real subprocesses on
    the real CLI path (VERDICT item 2: 'time kuke apply of a model-cell
    manifest -> first /v1/health 200').

    Never raises: returns (times, errors, per-run phase breakdowns read
    off each booted cell's kukeon_cold_start_* gauges). A failed run dumps
    the model-server + daemon logs to stderr before its run path is
    removed."""
    cli = [sys.executable, "-m", "kukeon_tpu.runtime.cli"]
    times: list[float] = []
    errors: list[str] = []
    phases: list[dict] = []
    for run in range(runs):
        run_path = tempfile.mkdtemp(prefix="kuke-bench-")
        socket_path = f"/tmp/kuked-bench-{uuid.uuid4().hex[:8]}.sock"
        daemon_log = os.path.join(run_path, "kukeond.log")
        port = 9600 + run
        env = subprocess_env()
        env.update({
            "KUKEON_TPU_CHIPS": chips,
            "KUKEOND_RECONCILE_INTERVAL": "1.0",
        })
        # hostNetwork: the bench host's chip is reachable only through the
        # host loopback (tunneled/emulated TPU runtime plane) and the timer
        # polls 127.0.0.1; the in-policy model-cell path is e2e-covered in
        # tests/test_netpolicy_e2e.py.
        manifest = (
            "apiVersion: kukeon.io/v1beta1\n"
            "kind: Cell\n"
            "metadata: {name: llm}\n"
            "spec:\n"
            f"  model: {{model: {model}, chips: 1, port: {port}, numSlots: 4"
            + (f", checkpoint: {checkpoint}" if checkpoint else "")
            + ", maxSeqLen: 1024, hostNetwork: true}\n"
        )
        with open(daemon_log, "wb") as dlog:
            daemon = subprocess.Popen(
                cli + ["daemon", "serve", "--run-path", run_path,
                       "--socket", socket_path],
                env=env, stdout=dlog, stderr=subprocess.STDOUT,
            )
        try:
            deadline = time.monotonic() + 15
            while not os.path.exists(socket_path):
                if time.monotonic() > deadline:
                    raise RuntimeError("daemon socket did not appear")
                time.sleep(0.05)
            t0 = time.monotonic()
            subprocess.run(
                cli + ["--socket", socket_path, "--run-path", run_path,
                       "apply", "-f", "-"],
                input=manifest, text=True, env=env, check=True,
                capture_output=True, timeout=120,
            )
            health = f"http://127.0.0.1:{port}/v1/health"
            budget = float(os.environ.get("KUKEON_BENCH_HEALTH_TIMEOUT", "600"))
            deadline = time.monotonic() + budget
            while True:
                try:
                    with urllib.request.urlopen(health, timeout=2) as r:
                        if r.status == 200:
                            break
                except OSError:
                    pass
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"model cell not healthy in {budget:.0f}s (run {run})"
                    )
                time.sleep(0.25)
            dt = time.monotonic() - t0
            times.append(dt)
            ph = _cold_start_phases(port)
            if ph:
                phases.append(ph)
                _log(f"cold start run {run}: {dt:.1f}s "
                     + " ".join(f"{k}={v}s" for k, v in sorted(ph.items())))
            else:
                _log(f"cold start run {run}: {dt:.1f}s")
            subprocess.run(
                cli + ["--socket", socket_path, "--run-path", run_path,
                       "delete", "cell", "llm", "--force"],
                env=env, capture_output=True, timeout=120,
            )
        except Exception as e:  # noqa: BLE001 — a lost run must not lose the bench
            errors.append(f"run {run}: {e}")
            _log(f"cold start run {run} FAILED: {e}")
            _dump_evidence(run_path, daemon_log, cli, socket_path, env, run)
        finally:
            daemon.terminate()
            try:
                daemon.wait(timeout=5)
            except subprocess.TimeoutExpired:
                daemon.kill()
            import shutil

            shutil.rmtree(run_path, ignore_errors=True)
            if os.path.exists(socket_path):
                os.unlink(socket_path)
    return times, errors, phases


# --- orchestrator -------------------------------------------------------------

def _cold_summary(runs_s: list[float], errors: list[str],
                  phases: list[dict], model: str) -> dict:
    """The artifact's cold_start section from measure_cold_starts output."""
    cold: dict = {
        "target_s": COLD_START_TARGET_S,
        "runs_s": [round(t, 1) for t in sorted(runs_s)],
        "model": model,
    }
    if runs_s:
        s = sorted(runs_s)
        cold["p50_s"] = round(s[len(s) // 2], 1)
    if phases:
        # Per-run boot-phase breakdowns (kukeon_cold_start_phase_seconds
        # read off each booted cell): the artifact names where cold-start
        # time goes, not just how much of it there was.
        cold["phases_s"] = phases
        # v6: the streamed-load sub-phases (disk / cast / upload) are
        # WORK-TIME ledgers overlapped with each other and with compile,
        # summarized as medians — so sum(phases) > total is the overlap
        # evidence, not an accounting bug.
        load = {}
        for stage in ("disk", "cast", "upload"):
            vals = sorted(p[stage] for p in phases if stage in p)
            if vals:
                load[stage] = round(vals[len(vals) // 2], 3)
        if load:
            cold["load_s"] = load
    if errors:
        cold["error"] = "; ".join(errors)[-500:]
    return cold


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--phase", default="all",
                    choices=["all", "serve", "embed", "ab", "autotune",
                             "gateway", "mixed", "disagg", "diurnal",
                             "profile-layers"])
    # Diurnal ramp through the gateway + spillover (phase_diurnal): the
    # night->peak->trough arrival schedule with a deliberately
    # under-provisioned fleet; the headline numbers are zero client-visible
    # 429s during the peak's shed storm and the per-stage client p95.
    ap.add_argument("--diurnal", action="store_true")
    # Mixed agent-session workload at fixed KV HBM (phase_mixed): legacy
    # vs paged engine, max concurrent sessions + aggregate tok/s per arm.
    ap.add_argument("--mixed", action="store_true")
    # Disaggregated prefill/decode acceptance bench (phase_disagg, run as
    # `--mixed --disagg`): the bimodal workload against a 1-prefill +
    # 1-decode split vs the same cells mixed, through the real gateway;
    # client-side TTFT p95 per arm + the handoff cost histogram.
    ap.add_argument("--disagg", action="store_true")
    # Scale-out routing benchmark: stand up a replica gateway + N in-process
    # replicas and measure aggregate tok/s + retry rate through the proxy.
    ap.add_argument("--replicas", type=int, default=1)
    # Sweep the serving perf levers and persist the winner to the tune
    # profile that ServingEngine/ServingCell read at boot (phase_autotune).
    ap.add_argument("--autotune", action="store_true")
    # Per-layer cost profiling (phase_profile_layers): lower each model
    # component individually, record cost-analysis FLOPs/bytes + wall
    # time, persist next to the serving tune for `kuke profile layers`.
    ap.add_argument("--profile-layers", action="store_true")
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--decode-chunk", type=int,
                    default=int(os.environ.get("KUKEON_BENCH_CHUNK", "16")))
    # int8 KV cache (halves the per-step cache HBM stream; the win grows
    # with context length and slot count — at the default 4x1024 shapes the
    # cache is ~6% of step bytes next to 8 GB of int8 weights).
    ap.add_argument("--kv-int8", action="store_true",
                    default=os.environ.get("KUKEON_BENCH_KV_INT8", "") == "1")
    # Comma-separated prefill bucket ladder override (e.g. "256,1024,4096").
    ap.add_argument("--prefill-buckets", default=None)
    # Paged KV cache page size (serving/kv_pages.py): 0/absent = legacy
    # contiguous layout; > 0 = block-table page pool with this page size.
    ap.add_argument("--kv-page-tokens", type=int, default=None)
    # Sharding layout (serve phase): exact N-chip tensor-parallel mesh
    # (absent = every visible device, auto-factorized) and whether the KV
    # pool shards over the tensor axis (auto = the engine's divisibility
    # default). The autotune sweep drives both.
    ap.add_argument("--chips", type=int, default=None)
    ap.add_argument("--kv-shard", choices=("auto", "on", "off"),
                    default="auto")
    # Fast mode: measure the streamed-boot cold start ONLY (fresh daemon ->
    # apply -> first health, with the disk/cast/upload/compile breakdown
    # off the cell's own gauges) and skip the serve/flood phases entirely —
    # the boot-pipeline iteration loop in seconds, not minutes.
    ap.add_argument("--cold-start-only", action="store_true")
    ap.add_argument("--cold-runs", type=int, default=None,
                    help="override the number of cold-start runs")
    # Standardized trajectory artifact (e.g. --out BENCH_r06.json): one
    # schema-versioned JSON file per run (kukeon-bench/v8; read_artifact
    # upgrades v1-v7 points) with percentiles, throughput, compile counts,
    # peak HBM, replica count, and the disaggregation + diurnal sections,
    # so BENCH_*.json points stay comparable across rounds regardless of
    # how the console line evolves.
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    if args.autotune or args.phase == "autotune":
        phase_autotune(args)
        return
    if args.profile_layers or args.phase == "profile-layers":
        phase_profile_layers(args)
        return
    if args.disagg or args.phase == "disagg":
        phase_disagg(args)
        return
    if args.diurnal or args.phase == "diurnal":
        phase_diurnal(args)
        return
    if args.mixed or args.phase == "mixed":
        phase_mixed(args)
        return
    if args.phase == "gateway" or args.replicas > 1:
        phase_gateway(args)
        return
    if args.phase == "serve":
        phase_serve(args)
        return
    if args.phase == "embed":
        phase_embed(args)
        return
    if args.phase == "ab":
        phase_ab(args)
        return

    backend, n_chips = detect_backend()
    _log(f"backend={backend} n_chips={n_chips}")

    qdir = None
    if backend != "cpu":
        try:
            qdir = ensure_quantized_8b()
        except Exception as e:  # noqa: BLE001 — degrade, don't die numberless
            _log(f"8B checkpoint prep failed ({e}); degrading to CPU smoke")
            os.environ["JAX_PLATFORMS"] = "cpu"
            backend = "cpu"
    cold_model, cold_runs = ("llama3-8b", 3) if qdir else ("tiny", 1)
    if args.cold_runs is not None:
        cold_runs = args.cold_runs

    if args.cold_start_only:
        try:
            runs_s, errs, ph = measure_cold_starts(
                cold_model, qdir, cold_runs,
                chips=os.environ.get("KUKEON_TPU_CHIPS", "0"))
        except Exception as e:  # noqa: BLE001
            runs_s, errs, ph = [], [f"harness: {e}"], []
        result = {"cold_start": _cold_summary(runs_s, errs, ph, cold_model)}
        if args.out:
            # The serve phase never ran: the artifact records the boot
            # breakdown with the serve fields explicitly null, so trend
            # tooling sees "not measured", not "measured zero".
            write_artifact(args.out, {
                "backend": backend, "n_chips": n_chips, "model": cold_model,
                "sessions": None, "tok_per_s": 0.0, "trials": 0,
            }, result)
        print(json.dumps(result))
        return

    def run_serve(checkpoint: str | None):
        # Serve phase in its own process (exits -> releases the chip for
        # the cold-start daemons).
        cmd = [sys.executable, os.path.abspath(__file__), "--phase", "serve",
               "--decode-chunk", str(args.decode_chunk)]
        if args.kv_int8:
            cmd += ["--kv-int8"]
        if checkpoint:
            cmd += ["--checkpoint", checkpoint]
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=3600, cwd=REPO, env=subprocess_env())
        sys.stderr.write(out.stderr[-8000:])
        if out.returncode != 0:
            raise RuntimeError(f"serve phase rc={out.returncode}")
        return json.loads(out.stdout.strip().splitlines()[-1])

    try:
        serve = run_serve(qdir)
    except Exception as e:  # noqa: BLE001 — a TPU serve failure must not zero the bench
        if backend == "cpu":
            raise
        _log(f"TPU serve phase failed ({e}); falling back to CPU smoke")
        os.environ["JAX_PLATFORMS"] = "cpu"
        backend, qdir = "cpu", None
        cold_model, cold_runs = "tiny", 1
        serve = run_serve(None)
    # Bank the measured number the moment it exists: everything after this
    # point appends to the result, never destroys it (VERDICT r4 weak 1 —
    # r4's measured 8B TPU throughput was discarded when cold-start raised).
    _log(f"serve phase result: {json.dumps(serve)}")

    # Embedding throughput (config 5) — auxiliary measurement, never fatal.
    embedding = None
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--phase", "embed"],
            capture_output=True, text=True, timeout=1200, cwd=REPO,
            env=subprocess_env(),
        )
        if out.returncode == 0:
            embedding = json.loads(out.stdout.strip().splitlines()[-1])
            _log(f"embed phase result: {json.dumps(embedding)}")
        else:
            _log(f"embed phase failed rc={out.returncode}:\n{out.stderr[-1500:]}")
    except Exception as e:  # noqa: BLE001
        _log(f"embed phase error: {e}")

    baseline_share = 1500.0 * serve["n_chips"] / 8.0
    result = {
        "metric": "aggregate decode tok/s, %d concurrent sessions, %s, %d chip(s) [%s]"
                  % (serve["sessions"], serve["model"], serve["n_chips"],
                     serve["backend"]),
        "value": round(serve["tok_per_s"], 2),
        "unit": "tok/s",
        "vs_baseline": round(serve["tok_per_s"] / baseline_share, 4),
        "trials": serve["trials"],
        # p50/p95/p99 TTFT / inter-token / e2e from the serving engine's
        # own obs histograms (the same ones /metrics exposes in prod).
        "latency_s": serve.get("latency_s"),
    }

    try:
        cold_runs_s, cold_errors, cold_phases = measure_cold_starts(
            cold_model, qdir, cold_runs,
            chips=os.environ.get("KUKEON_TPU_CHIPS", "0"),
        )
    except Exception as e:  # noqa: BLE001 — belt over measure's own no-raise
        cold_runs_s, cold_errors, cold_phases = [], [f"harness: {e}"], []
    cold = _cold_summary(cold_runs_s, cold_errors, cold_phases, cold_model)
    result["cold_start"] = cold
    if embedding is not None:
        result["embedding"] = embedding

    # TPU measurement history (committed): a genuine TPU number must survive
    # a later flaky-tunnel run. On a TPU measurement, append it; on a
    # CPU-degraded run, reference the last recorded TPU result so the
    # artifact names what the hardware did when it was reachable.
    history = os.path.join(REPO, "BENCH_TPU_HISTORY.jsonl")
    if serve["backend"] == "tpu":
        try:
            entry = {
                "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                "backend": "tpu", "n_chips": serve["n_chips"],
                "model": serve["model"], "sessions": serve["sessions"],
                "tok_per_s": round(serve["tok_per_s"], 2),
                "trials": serve["trials"],
                "vs_baseline": result["vs_baseline"],
                "latency_s": serve.get("latency_s"),
                "cold_start": cold,
            }
            with open(history, "a") as f:
                f.write(json.dumps(entry) + "\n")
        except OSError:
            pass
    else:
        try:
            with open(history) as f:
                lines = [ln for ln in f.read().splitlines() if ln.strip()]
            if lines:
                last = json.loads(lines[-1])
                result["last_tpu"] = {
                    "at": last.get("at"),
                    "tok_per_s": last.get("tok_per_s"),
                    "vs_baseline": last.get("vs_baseline"),
                    "cold_start_p50_s": (last.get("cold_start") or {}).get("p50_s"),
                    "note": "most recent real-TPU measurement (this run degraded to CPU)",
                }
        except (OSError, ValueError):
            pass
    if args.out:
        write_artifact(args.out, serve, result)
    print(json.dumps(result))


def read_artifact(path: str) -> dict:
    """Read a BENCH_rNN.json trajectory artifact, upgrading older schemas
    in place so trajectory tooling compares one shape across rounds: a
    kukeon-bench/v1 point (pre-gateway) is a single-engine measurement and
    gains ``replicas: 1``; v1/v2 points (pre-paged-KV) gain
    ``kv_page_tokens: 0`` (the legacy contiguous layout) and
    ``max_sessions`` equal to their session count; v1–v3 points
    (pre-disaggregation) gain ``ttft_p95_s`` (lifted from their latency
    percentiles when present), ``handoff_ms_p50: None`` (no KV handoff
    existed), and ``disagg: None``; v1–v4 points (pre-autoscaling) gain
    ``diurnal: None`` (no diurnal-ramp phase existed); v1–v5 points
    (pre-streamed-boot) gain ``cold_start.load_s: None`` (no disk / cast /
    upload sub-phase ledger existed before the streamed checkpoint
    pipeline); v1–v6 points (pre-multi-chip) gain ``mesh: None`` (the
    measurement ran before the sharded serving mesh existed); v1–v7
    points (pre-roofline) gain ``program_costs: None`` and ``mfu: None``
    (no per-program timer/cost instrumentation existed — a v8 point
    always records both when the serve phase ran)."""
    with open(path) as f:
        artifact = json.load(f)
    schema = artifact.get("schema")
    if schema not in ("kukeon-bench/v1", "kukeon-bench/v2",
                      "kukeon-bench/v3", "kukeon-bench/v4",
                      "kukeon-bench/v5", "kukeon-bench/v6",
                      "kukeon-bench/v7", "kukeon-bench/v8"):
        raise ValueError(f"unknown bench artifact schema {schema!r} in {path}")
    if schema != "kukeon-bench/v8":
        artifact = dict(artifact)
        artifact.setdefault("replicas", 1)              # v1 -> v2
        artifact.setdefault("kv_page_tokens", 0)        # v2 -> v3
        artifact.setdefault("max_sessions", artifact.get("sessions"))
        lat = ((artifact.get("latency_s") or {}).get("ttft") or {})
        artifact.setdefault("ttft_p95_s", lat.get("p95"))   # v3 -> v4
        artifact.setdefault("handoff_ms_p50", None)
        artifact.setdefault("disagg", None)
        artifact.setdefault("diurnal", None)            # v4 -> v5
        if isinstance(artifact.get("cold_start"), dict):    # v5 -> v6
            artifact["cold_start"] = dict(artifact["cold_start"])
            artifact["cold_start"].setdefault("load_s", None)
        artifact.setdefault("mesh", None)               # v6 -> v7
        artifact.setdefault("program_costs", None)      # v7 -> v8
        artifact.setdefault("mfu", None)
        artifact["schema"] = "kukeon-bench/v8"
    return artifact


def write_artifact(path: str, serve: dict, result: dict) -> None:
    """The standardized BENCH_rNN.json trajectory point: fixed schema, one
    file per run, every field from the product's own instruments."""
    artifact = {
        "schema": "kukeon-bench/v8",
        "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "backend": serve["backend"],
        "n_chips": serve["n_chips"],
        "model": serve.get("model_id") or serve["model"],
        # v2: how many serving engines stood behind the measurement (the
        # gateway phase sets >1; the classic serve phase is one engine).
        "replicas": serve.get("replicas", 1),
        "sessions": serve["sessions"],
        "tok_per_s": round(serve["tok_per_s"], 2),
        "trials": serve["trials"],
        "vs_baseline": result.get("vs_baseline"),
        # p50/p95/p99 for ttft / inter_token / e2e (engine histograms).
        "latency_s": serve.get("latency_s"),
        "compiles": serve.get("compiles"),
        "peak_hbm_bytes": serve.get("peak_hbm_bytes"),
        # v3: KV page size the measured engine served from (0 = legacy
        # contiguous layout) and the peak number of concurrently resident
        # sessions — the paged cache's headline number (--mixed drives it
        # past the legacy slot count at equal HBM).
        "kv_page_tokens": serve.get(
            "kv_page_tokens", (serve.get("config") or {}).get(
                "kv_page_tokens", 0)),
        "max_sessions": serve.get("max_sessions", serve.get("sessions")),
        # v4: client-observable TTFT p95 (lifted from the engine latency
        # percentiles when the phase measured no client-side number), and
        # the disaggregated-serving section (KV handoff cost + per-arm
        # TTFT/throughput) when `--mixed --disagg` produced one.
        "ttft_p95_s": serve.get(
            "ttft_p95_s",
            ((serve.get("latency_s") or {}).get("ttft") or {}).get("p95")),
        "handoff_ms_p50": result.get("handoff_ms_p50"),
        "disagg": result.get("disagg"),
        # v5: the diurnal-ramp section (per-stage qps/p95/statuses plus
        # the gateway spillover outcome counters) when `--diurnal` ran.
        "diurnal": result.get("diurnal"),
        "cold_start": result.get("cold_start"),
        "embedding": result.get("embedding"),
        "mixed": result.get("mixed"),
        # v7: the serving-mesh layout the measurement ran on (chips,
        # tensor-axis size, whether the KV pool sharded); None only for
        # phases that never built an engine (e.g. --cold-start-only).
        "mesh": serve.get("mesh"),
        # v8: the roofline section — per-program dispatch/wall/token
        # counters with their static cost-analysis FLOPs/bytes (the
        # ProgramTimers snapshot) and the headline MFU; None for phases
        # that never ran the serve loop.
        "program_costs": serve.get("program_costs"),
        "mfu": serve.get("mfu"),
    }
    # v6: cold_start carries the streamed-load sub-phase ledger (disk /
    # cast / upload medians); explicit None when the boot exported none.
    if isinstance(artifact["cold_start"], dict):
        artifact["cold_start"].setdefault("load_s", None)
    try:
        with open(path, "w") as f:
            json.dump(artifact, f, indent=1)
            f.write("\n")
        _log(f"wrote trajectory artifact {path}")
    except OSError as e:
        _log(f"could not write {path}: {e}")


if __name__ == "__main__":
    main()
