"""Benchmark: aggregate agent-serving decode throughput (tok/s).

Mirrors the BASELINE.json north-star shape — N concurrent coding-agent
sessions decoding against one shared model — scaled to the chips actually
present. The 8-chip target is 1500 aggregate tok/s for Llama-3-8B on v5e-8;
``vs_baseline`` compares against the pro-rata per-chip share of that target
(1500 * n_chips / 8).

Round-1 note: a single v5e chip (16 GB HBM) cannot hold Llama-3-8B bf16, so
the single-chip benchmark serves the Llama-3.2-1B shape; the JSON labels the
model so the number is not mistaken for an 8B measurement.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "tok/s", "vs_baseline": N, ...}
"""

import json
import time

import numpy as np


def main():
    import jax

    from kukeon_tpu.models import llama
    from kukeon_tpu.parallel import make_mesh, auto_mesh_shape
    from kukeon_tpu.serving import SamplingParams, ServingEngine

    backend = jax.default_backend()
    n_chips = len(jax.devices())

    if backend == "cpu":
        cfg = llama.llama_tiny()
        sessions, prompt_len, new_tokens, max_seq = 2, 32, 16, 128
        model_name = "tiny (cpu smoke)"
    else:
        cfg = llama.llama3_1b()
        sessions, prompt_len, new_tokens, max_seq = 4, 128, 128, 1024
        model_name = "llama3.2-1b-shape"

    shape = auto_mesh_shape(n_chips)
    mesh = make_mesh(data=shape["data"], tensor=shape["tensor"])

    params = llama.init_params(jax.random.key(0), cfg)
    engine = ServingEngine(
        cfg, params, mesh, num_slots=sessions, max_seq_len=max_seq
    )

    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, cfg.vocab_size, size=prompt_len).astype(np.int32)
        for _ in range(sessions)
    ]
    sp = SamplingParams(max_new_tokens=new_tokens)

    # Warmup: compile prefill (same bucket as the measured prompts), insert,
    # and the decode-chunk programs.
    engine.warmup(prompt_len, sp)

    # The chip link (tunnel) has high latency jitter; a single short run can
    # swing +-30%. Measure several trials and report the median.
    trials = 1 if backend == "cpu" else 3
    rates = []
    for _ in range(trials):
        t0 = time.monotonic()
        reqs = [engine.submit(p, sp) for p in prompts]
        while not all(r.done.is_set() for r in reqs):
            engine.step()
        dt = time.monotonic() - t0
        total_tokens = sum(len(r.generated) for r in reqs)
        rates.append(total_tokens / dt)
    rates.sort()
    toks_per_s = rates[len(rates) // 2]

    baseline_share = 1500.0 * n_chips / 8.0
    print(json.dumps({
        "metric": "aggregate decode tok/s, %d concurrent sessions, %s, %d chip(s) [%s]"
                  % (sessions, model_name, n_chips, backend),
        "value": round(toks_per_s, 2),
        "unit": "tok/s",
        "vs_baseline": round(toks_per_s / baseline_share, 4),
        "trials": [round(r, 1) for r in rates],
    }))


if __name__ == "__main__":
    main()
